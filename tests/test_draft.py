"""Real-draft speculative decoding (runtime/draft.py).

The invariants everything hangs on:

  * GREEDY BIT-PARITY — draft-on output is EXACTLY the plain greedy
    stream, single-stream and through the slot scheduler (mid-decode
    joins, slot reuse included): drafts only batch the confirmation, on
    arbitrary text. A stale/unseeded/garbage draft cache can only lower
    the accept rate, never change a token.
  * SAMPLED EXACTNESS — the general rejection-resampling step
    (speculative.accept_or_resample_q) is marginal-exact against a
    NON-point-mass proposal distribution q (a real draft model's own
    softmax), and the end-to-end sampled self-draft stream's marginals
    match the host sampler's.
  * DRAFT-KV LIFECYCLE — per-slot draft state resets with every lease
    (finish / cancel / deadline / abort), supervisor crash-recovery
    rebuilds the draft over the fresh engine, and speculative serving
    mints ZERO post-warmup compile keys (the bounded-key discipline
    --freeze-compiles enforces).
"""

import time

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.models.params import load_params, random_tensors
from distributed_llama_tpu.runtime.draft import (DraftModel, build_draft,
                                                 parse_draft_spec)
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.runtime.faults import FAULTS
from distributed_llama_tpu.runtime.profiler import COMPILES
from distributed_llama_tpu.runtime.scheduler import RequestError, Scheduler
from distributed_llama_tpu.sampler import Sampler

SEQ = 96


@pytest.fixture(scope="module")
def tiny():
    spec = ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=4, n_heads=8, n_kv_heads=4, vocab_size=128,
                     seq_len=SEQ, hidden_act=HiddenAct.SILU)
    host = random_tensors(spec, seed=41, scale=0.05)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    return spec, params


def _engine(tiny, batch=1):
    spec, params = tiny
    return Engine(spec, params, batch=batch, compute_dtype=jnp.float32,
                  cache_dtype=jnp.float32)


def _greedy(spec):
    return Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=1,
                   backend="python")


def _oracle(tiny, prompt, max_tokens, eos_id=None):
    spec, _ = tiny
    return _engine(tiny).generate(prompt, max_tokens, _greedy(spec),
                                  eos_id=eos_id).tokens


def _run_until_done(sched, reqs, limit=500):
    for _ in range(limit):
        if all(r.finished.is_set() for r in reqs):
            return
        sched.step()
    raise AssertionError("scheduler did not drain within the step limit")


# -- draft spec / flag validation ----------------------------------------


def test_parse_draft_spec():
    assert parse_draft_spec("self:2") == ("self", "2")
    assert parse_draft_spec("model:/x/y.m") == ("model", "/x/y.m")
    for bad in ("self", "self:", "self:0", "self:-1", "self:two",
                "model:", "lookup:3", ""):
        with pytest.raises(ValueError):
            parse_draft_spec(bad)


def test_self_draft_depth_bounds(tiny):
    eng = _engine(tiny)
    with pytest.raises(ValueError, match="depth"):
        DraftModel.self_draft(eng, 0)
    with pytest.raises(ValueError, match="depth"):
        DraftModel.self_draft(eng, eng.spec.n_layers)  # full depth = no win
    d = DraftModel.self_draft(eng, 2)
    assert d.spec.n_layers == 2 and d.label == "self2"
    # zero extra weights: the sliced layer dicts ARE the target's objects
    assert all(a is b for a, b in zip(d.params["layers"],
                                      eng.params["layers"][:2]))


def test_cli_draft_dead_flag_validation(capsys):
    """Parse-time dead-flag discipline for the new --draft* flags: every
    bad combination dies BEFORE any model load."""
    from distributed_llama_tpu.apps import dllama

    base = ["generate", "--model", "x.m", "--tokenizer", "x.t"]
    cases = [
        (["--draft-len", "5"], "--draft-len has no effect"),
        (["--draft", "self:2", "--draft-len", "0"], "--draft-len must"),
        (["--draft", "self:2", "--lookup-decode", "5"], "--lookup-decode"),
        (["--draft", "bananas"], "--draft"),
        (["--draft", "self:0"], "--draft"),
        (["--draft", "model:/definitely/not/here.m"], "no such file"),
        (["--draft", "self:2", "--dp", "2"], "--dp"),
        (["--draft", "self:2", "--pp", "2"], "--pp"),
        (["--draft", "self:2", "--device-sampling"], "--device-sampling"),
    ]
    for extra, msg in cases:
        with pytest.raises(SystemExit) as ei:
            dllama.main(base + extra)
        assert msg in str(ei.value.code), (extra, ei.value.code)
    # api-mode refusal: --draft cannot reach pre-started --replica-hosts
    # workers (their configs are their operators') — a silently
    # plain-decoding fleet must be a parse-time error (review-found)
    with pytest.raises(SystemExit) as ei:
        dllama.main(["api", "--model", "x.m", "--tokenizer", "x.t",
                     "--serve-batch", "2", "--replica-hosts",
                     "h1:9001", "--draft", "self:2"])
    assert "--replica-hosts" in str(ei.value.code)


# -- greedy bit-parity ----------------------------------------------------


@pytest.mark.parametrize("depth,draft_len", [(1, 4), (2, 7), (3, 1)])
def test_self_draft_matches_plain_greedy(tiny, depth, draft_len):
    """Exact greedy parity across depths and draft lengths — accepted
    and rejected drafts must never change the emitted tokens (a tiny
    random model's truncated prefix disagrees often, so rejection paths
    run for real)."""
    prompt = [1, 5, 9, 1, 5]
    want = _oracle(tiny, prompt, 24)
    eng = _engine(tiny)
    d = DraftModel.self_draft(eng, depth)
    got = eng.generate_draft(prompt, 24, draft=d, draft_len=draft_len)
    assert got.tokens == want, (depth, draft_len)
    fwd, n = eng.last_accept_stats
    assert n == len(want) and fwd <= n + 1
    assert eng.last_spec["emitted"] == n


def test_self_draft_eos_and_budget_contracts(tiny):
    """Stop-token truncation inside a confirmed draft, pos rewind, and
    the budget-0 hard cap — the generate() contracts, draft-on."""
    prompt = [1, 5, 9, 1, 5]
    probe = _oracle(tiny, prompt, 16)
    eos = probe[5]
    want = _oracle(tiny, prompt, 16, eos_id=eos)
    eng = _engine(tiny)
    d = DraftModel.self_draft(eng, 2)
    out = eng.generate_draft(prompt, 16, eos_id=eos, draft=d, draft_len=5)
    assert out.tokens == want
    assert eng.pos == len(prompt) + len(want) - 1  # last token unstepped

    eng0 = _engine(tiny)
    d0 = DraftModel.self_draft(eng0, 2)
    assert eng0.generate_draft(prompt, 0, draft=d0).tokens == []
    assert eng0.pos == len(prompt)


def test_model_draft_file_matches_plain_greedy(tiny, tmp_path):
    """A separate draft .m (different dim/depth, same vocab) rides the
    same machinery at exact parity — its quality only moves the accept
    rate. A vocab-mismatched draft is refused."""
    from distributed_llama_tpu.testing import write_fixture

    spec, _ = tiny
    mpath, _ = write_fixture(tmp_path, rng=np.random.default_rng(9),
                             vocab_size=spec.vocab_size, dim=32,
                             n_layers=1, n_heads=4, n_kv_heads=2,
                             seq_len=SEQ)
    prompt = [1, 5, 9, 1, 5]
    want = _oracle(tiny, prompt, 16)
    eng = _engine(tiny)
    d = build_draft(eng, f"model:{mpath}")
    assert d.label == "model"
    got = eng.generate_draft(prompt, 16, draft=d, draft_len=4)
    assert got.tokens == want

    (tmp_path / "bad").mkdir(exist_ok=True)
    bad, _ = write_fixture(tmp_path / "bad",
                           rng=np.random.default_rng(9), vocab_size=64,
                           dim=32, n_layers=1, n_heads=4, n_kv_heads=2)
    with pytest.raises(ValueError, match="vocab"):
        build_draft(eng, f"model:{bad}")


# -- sampled exactness ----------------------------------------------------


def test_accept_or_resample_q_marginal_is_exact():
    """The general (non-point-mass q) rejection-resampling step, tested
    statistically: drawing d ~ q then accept/resample against p must
    reproduce p exactly — for q close to p, far from p, and with
    support mismatches in both directions."""
    from distributed_llama_tpu.runtime.speculative import (
        accept_or_resample_q, draw)

    rng = np.random.default_rng(11)
    p = np.asarray([0.5, 0.3, 0.15, 0.05])
    for q in (np.asarray([0.4, 0.35, 0.15, 0.1]),   # close
              np.asarray([0.05, 0.15, 0.3, 0.5]),   # far
              np.asarray([0.0, 0.6, 0.4, 0.0]),     # missing p's mode
              np.asarray([1.0, 0.0, 0.0, 0.0])):    # point mass
        counts = np.zeros(4)
        n = 40_000
        for _ in range(n):
            d = draw(q, rng.random())
            _, t = accept_or_resample_q(p, q, d, rng.random(),
                                        rng.random())
            counts[t] += 1
        np.testing.assert_allclose(counts / n, p, atol=0.012,
                                   err_msg=str(q))
    # q == p: acceptance is certain, rejection impossible
    assert accept_or_resample_q(p, p, 2, 0.999, 0.5) == (True, 2)


def test_draft_sampled_marginals_match_plain_sampling():
    """End-to-end: the sampled self-draft stream's position-0/1
    marginals must match the EXACT host-sampler distributions (the two
    use different RNGs, so only distributions can agree). The
    truncated-depth draft's q is a real non-point-mass distribution, so
    this exercises the min(1, p/q) accept and the max(p - q, 0)
    residual on every rejected round."""
    from distributed_llama_tpu.runtime.speculative import target_dist

    # PEAKED logits (scale 0.5): a flat tiny-model distribution's
    # nucleus is ~half the vocab and the TV noise floor at 300 runs
    # would swamp any real bias — the existing lookup marginal test
    # uses the same fixture scale for the same reason
    spec = ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=4, n_heads=8, n_kv_heads=4, vocab_size=128,
                     seq_len=SEQ, hidden_act=HiddenAct.SILU)
    host = random_tensors(spec, seed=43, scale=0.5)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    peaked = (spec, params)
    v = spec.vocab_size
    prompt = [1, 5, 9, 1, 5]
    n_runs = 300

    eng = _engine(peaked)
    lg0 = eng.fetch_logits(eng.prefill(prompt))[0]
    exact0 = target_dist(lg0, 0.8, 0.9, v)
    exact1 = np.zeros(v)
    for t1 in np.nonzero(exact0)[0]:
        eng.reset()
        eng.prefill(prompt)
        lg1 = eng.fetch_logits(
            eng.step(np.asarray([[t1]], np.int32), eng.pos))[0]
        exact1 += exact0[t1] * target_dist(lg1, 0.8, 0.9, v)

    eng.reset()
    d = DraftModel.self_draft(eng, 2)
    counts = np.zeros((2, v))
    plain = np.zeros((2, v))
    accepted_any = rejected_any = False
    for s in range(n_runs):
        eng.reset()
        res = eng.generate_draft_sampled(prompt, 3, draft=d,
                                         temperature=0.8, topp=0.9,
                                         seed=5000 + s, draft_len=2)
        fwd, n = eng.last_accept_stats
        accepted_any |= n > fwd
        rejected_any |= fwd >= 3
        for i in (0, 1):
            counts[i, res.tokens[i]] += 1
        # the plain host-sampler ensemble is the NOISE-FLOOR control:
        # position 1's nucleus here is ~120 tokens wide, so the
        # absolute TV floor at 300 runs is ~0.2 — only the control
        # makes the bound meaningful (measured: spec 0.221 vs control
        # 0.218 at 300; both halve at 900 — noise, not bias)
        eng.reset()
        toks = eng.generate(prompt, 3, Sampler(v, 0.8, 0.9,
                                               seed=90_000 + s,
                                               backend="python")).tokens
        for i in (0, 1):
            plain[i, toks[i]] += 1
    assert accepted_any and rejected_any  # both paths ran
    for i, exact in ((0, exact0), (1, exact1)):
        tv_spec = 0.5 * np.abs(counts[i] / n_runs - exact).sum()
        tv_plain = 0.5 * np.abs(plain[i] / n_runs - exact).sum()
        assert tv_spec < 0.3, (i, tv_spec, tv_plain)
        assert tv_spec < tv_plain + 0.08, (i, tv_spec, tv_plain)


def test_draft_sampled_deterministic_and_contracts(tiny):
    """Same seed -> identical stream; eos truncation and pos accounting
    match the greedy draft mode's contracts."""
    prompt = [1, 5, 9, 1, 5]
    runs = []
    for _ in range(2):
        eng = _engine(tiny)
        d = DraftModel.self_draft(eng, 2)
        runs.append(eng.generate_draft_sampled(
            prompt, 12, draft=d, temperature=0.8, topp=0.9,
            seed=7).tokens)
    assert runs[0] == runs[1] and len(runs[0]) == 12

    eos = runs[0][4]
    eng = _engine(tiny)
    d = DraftModel.self_draft(eng, 2)
    out = eng.generate_draft_sampled(prompt, 12, draft=d, temperature=0.8,
                                     topp=0.9, seed=7, eos_id=eos).tokens
    assert out == runs[0][: runs[0].index(eos) + 1]
    assert eng.pos == len(prompt) + len(out) - 1


# -- scheduler: per-slot drafts -------------------------------------------


def _spec_sched(tiny, batch=2, depth=2, draft_len=4, **kw):
    spec, _ = tiny
    eng = _engine(tiny, batch=batch)
    return Scheduler(eng, chunk=8,
                     draft_factory=lambda e: DraftModel.self_draft(e, depth),
                     draft_len=draft_len, draft_vocab=spec.vocab_size, **kw)


def test_scheduler_parity_mid_decode_join_and_slot_reuse(tiny):
    """Draft-on scheduler output == the sequential oracle through a
    mid-decode join AND a slot-reuse handoff (3 requests, 2 slots) —
    the continuous-batching twin of the single-stream parity test. The
    accept record lands on /stats."""
    spec, _ = tiny
    sched = _spec_sched(tiny)
    p0 = [1, 9, 23, 54, 7, 88, 101, 5, 61, 17, 3]
    p1 = [2, 40, 77, 12, 9]
    p2 = [5, 66, 31, 90, 14, 8, 55]
    r0 = sched.submit(p0, 24, _greedy(spec))
    for _ in range(3):  # 2 prefill chunks + 1 speculative decode step
        sched.step()
    assert not r0.finished.is_set()
    r1 = sched.submit(p1, 4, _greedy(spec))   # joins mid-decode of r0
    r2 = sched.submit(p2, 6, _greedy(spec))   # queued until a slot frees
    _run_until_done(sched, [r0, r1, r2])
    assert list(r0.tokens(timeout=5)) == _oracle(tiny, p0, 24)
    assert list(r1.tokens(timeout=5)) == _oracle(tiny, p1, 4)
    assert list(r2.tokens(timeout=5)) == _oracle(tiny, p2, 6)
    s = sched.stats.summary()
    assert s["spec"]["mode"] == "self2"
    assert s["spec"]["verify_forwards"] >= 1
    assert s["spec"]["drafted"] >= s["spec"]["accepted"] >= 0
    # per-request accept records populated too
    assert r0.stats.spec_forwards >= 1
    sched.close()


def test_scheduler_mixed_greedy_and_sampled_rows(tiny):
    """A sampled request rides the SAME verify forward (position-0
    logits) while its greedy neighbor speculates: the greedy row stays
    oracle-identical and the sampled row stays seed-deterministic vs a
    draft-OFF scheduler run."""
    spec, _ = tiny
    pg, ps = [1, 9, 23, 54], [2, 40, 77]

    def run(drafting):
        if drafting:
            sched = _spec_sched(tiny)
        else:
            sched = Scheduler(_engine(tiny, batch=2), chunk=8)
        rg = sched.submit(pg, 8, _greedy(spec))
        rs = sched.submit(ps, 8, Sampler(spec.vocab_size, 0.8, 0.9,
                                         seed=5, backend="python"))
        _run_until_done(sched, [rg, rs])
        out = (list(rg.tokens(timeout=5)), list(rs.tokens(timeout=5)))
        sched.close()
        return out

    on_g, on_s = run(True)
    assert on_g == _oracle(tiny, pg, 8)
    assert len(on_s) == 8  # sampled row served (determinism across
    # draft-on/off is NOT contractual: the sampled row's logits come
    # from a different executable — only the greedy rows pin bit-parity)


def test_draft_kv_resets_on_slot_reuse_cancel_and_deadline(tiny):
    """The draft-KV lifecycle bars: a slot freed by cancel or deadline
    hands a RESET draft frontier to its next lease, and the successor's
    output is oracle-identical (stale draft K/V can only have hurt the
    accept rate — parity proves the reset bookkeeping, the draft_pos
    assertions prove the frontier)."""
    spec, _ = tiny
    sched = _spec_sched(tiny, batch=1)  # one slot: reuse is forced
    r0 = sched.submit([1, 9, 23, 54], 30, _greedy(spec))
    for _ in range(6):
        sched.step()
    assert not r0.finished.is_set()
    s0 = sched.slots[0]
    assert s0.draft_pos > 0  # the draft really tracked the target
    r0.cancel()
    sched.step()
    assert r0.finish_reason == "cancelled"

    r1 = sched.submit([2, 40, 77], 4, _greedy(spec))
    sched.step()  # admission resets the lease
    assert s0.draft_pos <= len([2, 40, 77])  # frontier restarted at 0
    _run_until_done(sched, [r1])
    assert list(r1.tokens(timeout=5)) == _oracle(tiny, [2, 40, 77], 4)

    # deadline path: expires mid-decode, successor unaffected
    FAULTS.arm("slow_step", times=0, ms=25.0)
    try:
        r2 = sched.submit([5, 66, 31], 10_000, _greedy(spec),
                          deadline=time.perf_counter() + 0.2)
        with pytest.raises(RequestError) as ei:
            for _ in range(200):
                sched.step()
                if r2.finished.is_set():
                    list(r2.tokens(timeout=5))
                    break
        assert ei.value.code == "deadline"
    finally:
        FAULTS.clear()
    r3 = sched.submit([7, 3, 91, 4], 5, _greedy(spec))
    _run_until_done(sched, [r3])
    assert list(r3.tokens(timeout=5)) == _oracle(tiny, [7, 3, 91, 4], 5)
    sched.close()


def test_draft_frontier_clamped_to_verified_stream(tiny):
    """After every speculative round the slot's draft frontier must not
    exceed the verified stream (review-found: an inflated frontier past
    a rejection left rejected-token K/V below it, which intervening
    plain rounds — SLO degrade, budget tails — would then never heal,
    silently decaying the accept rate)."""
    spec, _ = tiny
    sched = _spec_sched(tiny, batch=1)
    r = sched.submit([1, 9, 23, 54], 20, _greedy(spec))
    saw_rejection = False
    for _ in range(200):
        if r.finished.is_set():
            break
        sched.step()
        s = sched.slots[0]
        if s.req is not None:
            assert s.draft_pos <= s.pos, (s.draft_pos, s.pos)
        blk = sched.stats.spec
        saw_rejection |= blk.drafted > blk.accepted
    assert r.finished.is_set()
    assert saw_rejection  # the clamp path really ran (random tiny
    # models reject often)
    assert list(r.tokens(timeout=5)) == _oracle(tiny, [1, 9, 23, 54], 20)
    sched.close()


def test_spec_serving_mints_zero_postwarmup_compiles(tiny):
    """The compile-sentinel bar: warmup compiles the WHOLE draft key set
    (draft prefill, draft scan, fixed-width verify), so a full
    speculative serve — staggered joins, slot reuse, catch-up chunks —
    mints ZERO post-warmup keys even with the ledger FROZEN."""
    spec, _ = tiny
    sched = _spec_sched(tiny)
    sched.warmup()
    before = COMPILES.after_warmup
    frozen = COMPILES.freeze
    COMPILES.freeze = True
    try:
        reqs = [sched.submit(p, 8, _greedy(spec))
                for p in ([1, 9, 23, 54, 7], [2, 40], [5, 66, 31])]
        _run_until_done(sched, reqs)
        for r, p in zip(reqs, ([1, 9, 23, 54, 7], [2, 40], [5, 66, 31])):
            assert list(r.tokens(timeout=5)) == _oracle(tiny, p, 8)
    finally:
        COMPILES.freeze = frozen
        sched.close()
    assert COMPILES.after_warmup - before == 0


def test_supervisor_crash_recovery_with_draft_armed(tiny):
    """Crash recovery with drafting armed (fault site step_raise): the
    dying generation's requests get structured frames, the rebuilt
    generation builds a FRESH DraftModel over the fresh engine, and the
    next request is oracle-identical — with its accept record live."""
    from distributed_llama_tpu.runtime.resilience import EngineSupervisor

    spec, params = tiny

    def factory():
        return Engine(spec, params, batch=2, compute_dtype=jnp.float32,
                      cache_dtype=jnp.float32)

    sup = EngineSupervisor(factory, chunk=8, stall_timeout=60.0,
                           backoff_base=0.01, breaker_threshold=5,
                           draft="self:2", draft_len=4,
                           draft_vocab=spec.vocab_size)
    try:
        p = [1, 9, 23, 54]
        FAULTS.arm("slow_step", times=0, ms=25.0)
        req = sup.submit(p, 40, _greedy(spec))
        it = req.tokens(timeout=30.0)
        got = [next(it)]
        draft0 = sup._sched.draft
        FAULTS.arm("step_raise")
        with pytest.raises(RequestError) as ei:
            for t in it:
                got.append(t)
        assert ei.value.code == "engine_error"
        deadline = time.perf_counter() + 30.0
        while not sup.ready and time.perf_counter() < deadline:
            time.sleep(0.01)
        assert sup.ready, sup.state
        FAULTS.clear()
        # the rebuilt generation drafts over ITS engine, not the dead one
        assert sup._sched.draft is not None
        assert sup._sched.draft is not draft0
        assert sup._sched.draft.engine is sup._sched.engine
        req2 = sup.submit(p, 6, _greedy(spec))
        assert list(req2.tokens(timeout=60.0)) == _oracle(tiny, p, 6)
        assert sup._sched.stats.spec.verify_forwards >= 1
    finally:
        FAULTS.clear()
        sup.close()


def test_admission_policy_degrades_speculation_under_slo_pressure(tiny):
    """The "degrade — no speculation" actuator: with an ITL SLO armed
    and steps running hot, the policy disables drafting (degraded
    iterations counted, plain decode keeps parity); when pressure
    clears, it re-arms."""
    from distributed_llama_tpu.runtime.scheduler import AdmissionPolicy

    pol = AdmissionPolicy(16, slo_itl_ms=50.0)
    assert pol.spec_allowed
    for _ in range(4):
        pol.observe_step(200.0, decode_rows=2, prefill_rows=0)
    assert not pol.spec_allowed and pol.spec_disables == 1
    for _ in range(10):
        pol.observe_step(5.0, decode_rows=2, prefill_rows=0)
    assert pol.spec_allowed and pol.spec_enables == 1
    assert pol.summary()["spec_allowed"] is True

    # end to end: a hot scheduler serves PLAIN (degraded_steps > 0) at
    # full parity
    spec, _ = tiny
    sched = _spec_sched(tiny, batch=1, slo_itl_ms=0.001)
    FAULTS.arm("slow_step", times=0, ms=5.0)
    try:
        r = sched.submit([1, 9, 23, 54], 6, _greedy(spec))
        _run_until_done(sched, [r])
        assert list(r.tokens(timeout=5)) == _oracle(tiny, [1, 9, 23, 54], 6)
        assert sched.stats.spec.degraded_steps > 0
        assert sched.stats.spec.verify_forwards <= 1  # at most the first
    finally:
        FAULTS.clear()
        sched.close()


# -- observability --------------------------------------------------------


def test_spec_stats_block_and_metrics_family(tiny):
    """The honest accept-rate surface: /stats carries a `spec` block in
    every scheduler state (mode "off" with no draft — the family never
    vanishes off a launch flag), and render_prometheus emits the
    dllama_spec_* family top-level AND per-replica."""
    from distributed_llama_tpu.runtime.trace import render_prometheus

    spec, _ = tiny
    sched = _spec_sched(tiny)
    r = sched.submit([1, 9, 23, 54], 6, _greedy(spec))
    _run_until_done(sched, [r])
    list(r.tokens(timeout=5))
    summ = sched.stats.summary()
    sched.close()
    blk = summ["spec"]
    assert blk["mode"] == "self2" and blk["draft_len"] == 4
    assert blk["drafted"] > 0 and 0.0 <= blk["accept_rate"] <= 1.0

    text = render_prometheus(summ)
    for name in ("dllama_spec_verify_forwards_total",
                 "dllama_spec_drafted_tokens_total",
                 "dllama_spec_accepted_tokens_total",
                 "dllama_spec_accept_rate", "dllama_spec_mode"):
        assert name in text, name
    # replica-shaped summary: the family rides the replica label
    text_r = render_prometheus({"replicas": [
        {"replica": 0, "state": "ready", "spec": blk}]})
    assert "dllama_replica_spec_accept_rate" in text_r

    # draft off: the block still answers, mode "off", zeros
    sched_off = Scheduler(_engine(tiny, batch=2), chunk=8)
    s_off = sched_off.stats.summary()
    sched_off.close()
    assert s_off["spec"]["mode"] == "off"
    assert s_off["spec"]["verify_forwards"] == 0
    assert "dllama_spec_mode" in render_prometheus(s_off)


def test_worker_config_ships_draft_and_factory_arms_it(tiny):
    """Process tier: the worker config carries the draft spec string
    (never buffers), and build_supervisor_factory arms per-slot
    drafting inside the worker's own supervisor — parity + live accept
    record, the same machinery the spawned tier runs."""
    from distributed_llama_tpu.apps import dllama
    from distributed_llama_tpu.runtime.replica_worker import (
        build_supervisor_factory, config_from_cli_args)

    args = dllama.build_argparser().parse_args([
        "api", "--model", "m.m", "--tokenizer", "t.t", "--serve-batch",
        "2", "--replica-procs", "2", "--draft", "self:2",
        "--draft-len", "3"])
    cfg = config_from_cli_args(args, 2)
    assert cfg["draft"] == "self:2" and cfg["draft_len"] == 3
    # --draft WITHOUT --draft-len: the 7 default applies in the shipped
    # config too (review-found: argparse's None sentinel shipped 0 and
    # tripped the worker Scheduler's draft_len >= 1 assertion)
    args_d = dllama.build_argparser().parse_args([
        "api", "--model", "m.m", "--tokenizer", "t.t", "--serve-batch",
        "2", "--replica-procs", "2", "--draft", "self:2"])
    assert config_from_cli_args(args_d, 2)["draft_len"] == 7
    args_n = dllama.build_argparser().parse_args([
        "api", "--model", "m.m", "--tokenizer", "t.t", "--serve-batch",
        "2", "--replica-procs", "2"])
    assert config_from_cli_args(args_n, 2)["draft_len"] == 0

    spec, _ = tiny
    wcfg = {"test_spec": dict(
        arch="LLAMA", dim=spec.dim, hidden_dim=spec.hidden_dim,
        n_layers=spec.n_layers, n_heads=spec.n_heads,
        n_kv_heads=spec.n_kv_heads, vocab_size=spec.vocab_size,
        seq_len=spec.seq_len), "seed": 7, "scale": 0.05,
        "compute_dtype": "f32", "batch": 2, "draft": "self:2",
        "draft_len": 3, "draft_vocab": spec.vocab_size,
        "serve": {"stall_timeout": 60.0}}
    sup = build_supervisor_factory(wcfg)()
    try:
        assert sup._sched.draft is not None
        assert sup._sched.draft_len == 3
        p = [1, 9, 23, 54]
        got = list(sup.submit(p, 6, Sampler(
            spec.vocab_size, 0.0, 0.9, 1,
            backend="python")).tokens(timeout=60.0))
        # oracle over the SAME synthetic weights the factory built
        from distributed_llama_tpu.models.params import (load_params,
                                                         random_tensors)
        params7 = load_params(spec, random_tensors(spec, seed=7,
                                                   scale=0.05),
                              mode="dense", dtype=jnp.float32)
        eng = Engine(spec, params7, compute_dtype=jnp.float32,
                     cache_dtype=jnp.float32)
        want = eng.generate(p, 6, Sampler(spec.vocab_size, 0.0, 0.9, 1,
                                          backend="python")).tokens
        assert got == want
        assert sup._sched.stats.spec.verify_forwards >= 1
    finally:
        sup.close()


def test_api_draft_decode_matches_plain(tmp_path):
    """API server, legacy path: greedy requests with --draft speculate
    (fewer forwards) with byte-identical responses; sampled requests
    ride the rejection-resampling stream (seed-deterministic); the
    legacy tier's aggregate `spec` block accumulates the accept record
    (the /stats + /metrics family every tier must carry)."""
    from distributed_llama_tpu.apps import dllama
    from distributed_llama_tpu.apps.api_server import (ApiState,
                                                       _completion_chunks)
    from distributed_llama_tpu.runtime.trace import render_prometheus
    from distributed_llama_tpu.testing import write_fixture

    rng = np.random.default_rng(19)
    mpath, tpath = write_fixture(tmp_path, rng=rng, seq_len=192)

    def build_state(draft):
        args = dllama.build_argparser().parse_args([
            "api", "--model", mpath, "--tokenizer", tpath,
            "--steps", "8", "--temperature", "0", "--seed", "3"])
        engine, tokenizer, sampler = dllama.build_engine(args)
        return ApiState(engine, tokenizer, sampler, draft=draft,
                        draft_len=4 if draft else 0)

    body = {"messages": [{"role": "user", "content": "abab"}],
            "max_tokens": 8, "temperature": 0}
    want = list(_completion_chunks(build_state(None), body))
    st = build_state("self:1")
    got = list(_completion_chunks(st, body))
    assert got == want
    fwd, n = st.engine.last_accept_stats
    assert n >= fwd  # speculation engaged
    blk = st.spec_stats.summary()
    assert blk["mode"] == "self:1" and blk["verify_forwards"] == fwd
    assert "dllama_spec_mode" in render_prometheus({"spec": blk})

    # sampled request: seed-deterministic through the rejection stream
    body_s = {"messages": [{"role": "user", "content": "abab"}],
              "max_tokens": 6, "temperature": 0.8, "seed": 11}
    st_a, st_b = build_state("self:1"), build_state("self:1")
    before = st_a.sampler.rng_state
    got_a = list(_completion_chunks(st_a, body_s))
    got_b = list(_completion_chunks(st_b, body_s))
    assert got_a == got_b
    assert st_a.sampler.rng_state == before  # per-request seed restored


def test_spec_trace_event_on_request_span(tiny):
    """The flight recorder gets one `spec` event per speculating request
    (forwards/drafted/accepted on the request's span) so dlprof can
    attribute verify-forward cost."""
    from distributed_llama_tpu.runtime.trace import EVENT_KINDS, TRACER

    assert "spec" in EVENT_KINDS
    spec, _ = tiny
    TRACER.configure(capacity=512, enabled=True)
    try:
        sched = _spec_sched(tiny)
        r = sched.submit([1, 9, 23, 54], 6, _greedy(spec))
        _run_until_done(sched, [r])
        list(r.tokens(timeout=5))
        sched.close()
        span = TRACER.by_id(r.trace_id)
        evs = [e for e in span if e["kind"] == "spec"]
        assert len(evs) == 1
        assert evs[0]["forwards"] >= 1
        assert evs[0]["drafted"] >= evs[0]["accepted"] >= 0
    finally:
        TRACER.reset()
