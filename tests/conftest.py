"""Test configuration: run everything on a virtual 8-device CPU mesh.

Multi-chip sharding is validated the way the reference validates multi-node
slicing without a cluster (ref: src/transformer-test.cpp:21-72 instantiates
all slices in one process) — but stronger: a real 8-device SPMD mesh via
XLA's host-platform device partitioning.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the session env may preset a TPU platform

# the 8-device convention lives in ONE place, shared with the dlgrind
# jaxpr audit and the multichip dryrun (utils/virtual_mesh.py is jax-free,
# so importing it here cannot initialize a backend early)
from distributed_llama_tpu.utils.virtual_mesh import \
    ensure_virtual_cpu_devices  # noqa: E402

ensure_virtual_cpu_devices()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

# a sitecustomize hook may have already pinned jax_platforms to a TPU plugin;
# override before any backend initializes
jax.config.update("jax_platforms", "cpu")
assert jax.device_count() == 8, jax.devices()

# persistent compilation cache: the suite's cost is dominated by XLA
# compiles of the SPMD mesh tests; cached executables cut a warm rerun
# drastically (VERDICT r4 #10). Keyed by jaxlib version internally, shared
# across local runs and CI steps.
jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.expanduser("~"), ".cache",
                               "dllama_tpu_xla"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import gc  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# the suite segfaults intermittently when Python's cyclic GC traverses
# jax tracing objects (faulthandler shows "Garbage-collecting" under
# partial_eval.to_jaxpr frames; an explicit between-test gc.collect()
# crashed the same way, so it is the traversal itself that is unsafe on
# this jaxlib/CPython pin, not its timing). Cyclic GC is disabled for the
# whole run: device buffers and most of the heap are refcount-freed as
# usual; only cyclic garbage accumulates, which a finite test session
# tolerates.
gc.collect()
gc.freeze()  # startup objects never become garbage — skip scanning them
gc.disable()

_exit_status: list = [None]


def pytest_sessionfinish(session, exitstatus):
    _exit_status[0] = int(exitstatus)


@pytest.hookimpl(trylast=True)
def pytest_unconfigure(config):
    # interpreter finalization runs a last GC pass over everything the
    # session accumulated, which crashes the same way (exit code 139 AFTER
    # the summary printed — the run looked like a segfault to CI). All
    # reporting is done by the time unconfigure fires: flush and leave
    # without finalizing, preserving pytest's real exit status.
    if _exit_status[0] is not None:
        import sys

        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(_exit_status[0])


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def forward_entry_inputs(arch: str = "LLAMA", *, batch: int = 1, t: int = 1,
                         spec=None, dtype=None):
    """Shared builder for abstract entry-point inputs — (spec, params,
    tokens, pos0, cache) for a forward() call. The SAME programs the
    analyzer's jaxpr audit traces (distributed_llama_tpu/analysis/
    entrypoints.py): test_hlo_wire.py lowers them to count collectives,
    the audit walks their jaxprs, and both stay in lock-step by
    construction."""
    import jax.numpy as jnp

    from distributed_llama_tpu.analysis.entrypoints import \
        build_forward_inputs

    return build_forward_inputs(spec, batch=batch, t=t,
                                dtype=dtype or jnp.float32, arch=arch)
