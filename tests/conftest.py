"""Test configuration: run everything on a virtual 8-device CPU mesh.

Multi-chip sharding is validated the way the reference validates multi-node
slicing without a cluster (ref: src/transformer-test.cpp:21-72 instantiates
all slices in one process) — but stronger: a real 8-device SPMD mesh via
XLA's host-platform device partitioning.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the session env may preset a TPU platform
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

# a sitecustomize hook may have already pinned jax_platforms to a TPU plugin;
# override before any backend initializes
jax.config.update("jax_platforms", "cpu")
assert jax.device_count() == 8, jax.devices()

# persistent compilation cache: the suite's cost is dominated by XLA
# compiles of the SPMD mesh tests; cached executables cut a warm rerun
# drastically (VERDICT r4 #10). Keyed by jaxlib version internally, shared
# across local runs and CI steps.
jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.expanduser("~"), ".cache",
                               "dllama_tpu_xla"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)
