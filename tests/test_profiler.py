"""Device-tier observability (runtime/profiler.py): the compile ledger +
recompile sentinel, the HBM ledger, on-demand capture, sampled
device-time attribution, and build info — the ISSUE 10 acceptance bars:

  * ZERO post-warmup compiles across the legacy / supervisor / router
    serving paths on the existing traffic shapes (the runtime twin of
    dlgrind's static fingerprint gate), including across a supervisor
    crash-recovery rebuild;
  * a deliberately minted NEW compile key (an unregistered prefill
    chunk width) fires ``compile_after_warmup`` — and, under
    ``--freeze-compiles``, a structured ``RequestError`` BEFORE the
    compile runs;
  * the HBM ledger's slot/arena byte counts match the engine's
    allocated shapes EXACTLY on CPU-tiny (they are real ``nbytes``);
  * profiler disabled is allocation-free on the hot path
    (guard-before-call, the tracer's <50-blocks discipline) and the
    per-step cost of the sampling guard is ≤ 2% of a real tiny-model
    decode step (the least favorable denominator).
"""

import json
import os
import sys
import time

import pytest

jnp = pytest.importorskip("jax.numpy")

from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.models.params import load_params, random_tensors
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.runtime.profiler import (COMPILES, PROFILER,
                                                    build_info,
                                                    compile_key_str,
                                                    hbm_ledger)
from distributed_llama_tpu.runtime.scheduler import RequestError, Scheduler
from distributed_llama_tpu.runtime.trace import TRACER
from distributed_llama_tpu.sampler import Sampler

SEQ = 64


@pytest.fixture(scope="module")
def tiny():
    spec = ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=128, seq_len=SEQ,
                     hidden_act=HiddenAct.SILU)
    host = random_tensors(spec, seed=3, scale=0.05)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    return spec, params


@pytest.fixture(autouse=True)
def clean_ledgers():
    COMPILES.reset()
    PROFILER.reset()
    TRACER.reset()
    yield
    COMPILES.reset()
    PROFILER.reset()
    TRACER.reset()


def _engine(tiny, batch=2):
    spec, params = tiny
    return Engine(spec, params, batch=batch, compute_dtype=jnp.float32,
                  cache_dtype=jnp.float32)


def _greedy(spec):
    return Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=1)


# -- compile ledger ---------------------------------------------------------


def test_ledger_records_every_mint_with_key_and_ms(tiny):
    spec, _ = tiny
    eng = _engine(tiny, batch=1)
    before = COMPILES.total  # 0 on CPU: an unsharded engine's cache is
    # built eagerly, the jitted cache maker exists only on meshes
    eng.generate([1, 9, 23, 54, 7], 3, _greedy(spec))
    s = COMPILES.summary()
    assert s["total"] > before          # prefill seg + decode step minted
    assert s["after_warmup"] == 0       # nothing marked warm yet
    assert s["total_ms"] > 0.0
    assert "seg:1" in s["by_key"]       # the decode step's key
    rec = s["by_key"]["seg:1"]
    assert rec["count"] == 1 and rec["ms"] > 0.0
    # steady state restored: the raw jitted callable is back in _steps
    # (the watch swapped itself out after the first call)
    from distributed_llama_tpu.runtime.profiler import _CompileWatch
    assert not isinstance(eng._steps[1], _CompileWatch)


def test_key_strings_are_label_safe():
    assert compile_key_str(1) == "seg:1"
    assert compile_key_str("slot_decode") == "slot_decode"
    assert compile_key_str(("slot_prefill", 16)) == "slot_prefill:16"
    ks = compile_key_str(("prefix_arena", (16, 2, 2, 4, 16)))
    assert ks == "prefix_arena:16x2x2x4x16"
    assert all(c.isalnum() or c in "_:.x-" for c in ks)


def test_zero_post_warmup_compiles_supervisor_traffic(tiny):
    """The supervisor tier acceptance bar: warmup compiles the serving
    set; the existing traffic shapes then mint NOTHING — every request
    rides slot_prefill_chunk_C + slot_decode_step."""
    from distributed_llama_tpu.runtime.resilience import EngineSupervisor

    spec, params = tiny
    sup = EngineSupervisor(lambda: Engine(spec, params, batch=2,
                                          compute_dtype=jnp.float32,
                                          cache_dtype=jnp.float32),
                           chunk=8, stall_timeout=60.0)
    try:
        assert COMPILES.after_warmup == 0
        for n in (3, 5, 9, 12):  # varied lengths: same chunked shapes
            req = sup.submit(list(range(1, n + 1)), 4, _greedy(spec))
            assert len(list(req.tokens(timeout=60.0))) >= 1
        assert COMPILES.after_warmup == 0, COMPILES.summary()
    finally:
        sup.close()


def test_zero_post_warmup_compiles_across_recovery(tiny):
    """A crash-recovery rebuild mints a FRESH engine whose own warmup
    legitimately recompiles the serving set — the sentinel must not
    misread those (the warm flag is per engine), and post-recovery
    traffic still mints nothing."""
    from distributed_llama_tpu.runtime.faults import FAULTS
    from distributed_llama_tpu.runtime.resilience import EngineSupervisor

    spec, params = tiny
    sup = EngineSupervisor(lambda: Engine(spec, params, batch=2,
                                          compute_dtype=jnp.float32,
                                          cache_dtype=jnp.float32),
                           chunk=8, stall_timeout=60.0,
                           backoff_base=0.01)
    try:
        FAULTS.arm("step_raise", after=0, times=1)
        req = sup.submit([1, 2, 3], 4, _greedy(spec))
        with pytest.raises(RequestError):
            list(req.tokens(timeout=60.0))
        deadline = time.perf_counter() + 60.0
        while not sup.ready and time.perf_counter() < deadline:
            time.sleep(0.02)
        assert sup.ready
        req = sup.submit([1, 9, 23, 54, 7], 4, _greedy(spec))
        assert len(list(req.tokens(timeout=60.0))) == 4
        assert COMPILES.after_warmup == 0, COMPILES.summary()
        assert sup.sup_stats.recoveries == 1
    finally:
        FAULTS.clear()
        sup.close()


def test_zero_post_warmup_compiles_router_traffic(tiny):
    """The thread-router tier: two warmed replicas over shared weights;
    routed traffic on the existing shapes mints nothing anywhere."""
    from distributed_llama_tpu.runtime.router import Router

    spec, params = tiny
    router = Router(lambda: Engine(spec, params, batch=2,
                                   compute_dtype=jnp.float32,
                                   cache_dtype=jnp.float32),
                    replicas=2, policy="round_robin", chunk=8,
                    stall_timeout=60.0)
    try:
        assert COMPILES.after_warmup == 0
        for _ in range(4):  # both replicas serve
            req = router.submit([1, 9, 23, 54, 7], 3, _greedy(spec))
            assert len(list(req.tokens(timeout=60.0))) == 3
        assert COMPILES.after_warmup == 0, COMPILES.summary()
        assert router.summary()["compiles"]["after_warmup"] == 0
    finally:
        router.close()


def test_legacy_repeat_shapes_mint_nothing(tiny):
    """The legacy tier's version of the bar: the first serve of a shape
    compiles; repeating the SAME shapes mints zero new executables."""
    spec, _ = tiny
    eng = _engine(tiny, batch=1)
    eng.generate([1, 9, 23, 54, 7], 3, _greedy(spec))
    before = COMPILES.total
    eng.reset()
    eng.generate([2, 8, 22, 50, 9], 3, _greedy(spec))  # same lengths
    assert COMPILES.total == before, COMPILES.summary()


def test_new_key_fires_sentinel_and_freeze_refuses(tiny):
    """The sentinel proven both ways: an unregistered chunk width on a
    WARM engine (1) records compile_after_warmup (event + counter), and
    (2) under freeze raises the structured error BEFORE compiling —
    unfreezing then compiles the same key fine (nothing was poisoned)."""
    import numpy as np

    spec, _ = tiny
    TRACER.configure(capacity=256)
    eng = _engine(tiny)
    sched = Scheduler(eng, chunk=8)
    sched.warmup()  # arms the sentinel (engine._compile_warm)
    assert eng._compile_warm

    gate = np.full((eng.batch,), eng.seq_len, np.int32)
    tok16 = np.zeros((eng.batch, 16), np.int32)  # unregistered width
    lidx = np.zeros((eng.batch,), np.int32)

    COMPILES.freeze = True
    with pytest.raises(RequestError) as ei:
        eng.slot_prefill_chunk(tok16, gate, lidx)
    assert ei.value.code == "compile_after_warmup"
    assert ei.value.retryable is False
    assert "slot_prefill:16" in str(ei.value)
    assert COMPILES.after_warmup == 1
    # refused BEFORE the compile: no record of the key was minted
    assert "slot_prefill:16" not in COMPILES.summary()["by_key"]

    COMPILES.freeze = False
    eng.slot_prefill_chunk(tok16, gate, lidx)  # now compiles (sentinel
    assert COMPILES.after_warmup == 2          # still counts the event)
    assert "slot_prefill:16" in COMPILES.summary()["by_key"]
    evs = [e for e in TRACER.recent(0)
           if e["kind"] == "compile_after_warmup"]
    assert len(evs) == 2
    assert evs[0]["key"] == "slot_prefill:16" and evs[0]["frozen"] is True
    sched.close()


# -- HBM ledger -------------------------------------------------------------


def test_hbm_ledger_matches_allocated_shapes_exactly(tiny):
    """The acceptance bar: slot/arena byte counts equal the engine's
    REAL allocated shapes on CPU-tiny (nbytes, not estimates)."""
    from distributed_llama_tpu.runtime.prefix_cache import PrefixCache

    spec, _ = tiny
    eng = _engine(tiny, batch=2)
    pc = PrefixCache(eng, num_blocks=16, block_len=4)
    led = hbm_ledger(eng, pc, device_stats=False)
    # KV slots: 2 (K+V) x layers x (B, KVH, S, HS) f32
    want_kv = 2 * spec.n_layers * 2 * spec.n_kv_heads * SEQ * \
        spec.head_size * 4
    assert led["kv_slot_bytes"] == want_kv
    assert led["kv_slot_bytes"] == sum(
        leaf.nbytes for leaf in list(eng.cache.k) + list(eng.cache.v))
    # arena: 2 x (16, layers, KVH, 4, HS) f32 — the real arrays
    want_arena = 2 * 16 * spec.n_layers * spec.n_kv_heads * 4 * \
        spec.head_size * 4
    assert led["prefix_arena_bytes"] == want_arena
    assert led["prefix_arena_bytes"] == (pc.arena_k.nbytes
                                         + pc.arena_v.nbytes)
    assert led["per_slot_bytes"] * eng.batch == led["kv_slot_bytes"]
    assert led["per_block_bytes"] * 16 == led["prefix_arena_bytes"]
    assert led["weights_bytes"] > 0
    # the vocab split-out (ISSUE-15): tok_emb + wcls land in their own
    # category and the accounted identity carries all five
    assert led["vocab_bytes"] > 0
    assert led["accounted_bytes"] == (
        led["weights_bytes"] + led["vocab_bytes"] + led["kv_slot_bytes"]
        + led["prefix_arena_bytes"] + led["logits_workspace_bytes"])
    # CPU backend: no allocator stats — nulls, never fabricated numbers
    cpu_led = hbm_ledger(eng, pc)
    if cpu_led["device_bytes_in_use"] is None:
        assert cpu_led["slots_addable"] is None
    json.dumps(led)  # /stats- and BENCH-ready


def test_hbm_block_rides_supervisor_stats(tiny):
    from distributed_llama_tpu.runtime.resilience import EngineSupervisor

    spec, params = tiny
    sup = EngineSupervisor(lambda: Engine(spec, params, batch=2,
                                          compute_dtype=jnp.float32,
                                          cache_dtype=jnp.float32),
                           chunk=8, stall_timeout=60.0,
                           prefix_blocks=8, prefix_block_len=4)
    try:
        s = sup.summary()
        assert s["hbm"]["kv_slot_bytes"] > 0
        assert s["hbm"]["prefix_arena_bytes"] > 0
        assert s["compiles"]["total"] >= 2  # the warmed serving set
        assert "device_time" not in s       # sampling off => no block
    finally:
        sup.close()


# -- disabled-path allocation + overhead bars -------------------------------


def test_profiler_disabled_is_allocation_free():
    assert PROFILER.sample_every == 0

    def guarded_loop(n):
        for _ in range(n):
            if PROFILER.sample_every:  # the scheduler's guard pattern
                PROFILER.step_begin()

    guarded_loop(10)  # warm code object/locals
    before = sys.getallocatedblocks()
    guarded_loop(10_000)
    grew = sys.getallocatedblocks() - before
    assert grew < 50, f"disabled guard allocated {grew} blocks"


def test_sampling_guard_overhead_two_percent_of_decode_step(tiny):
    """ISSUE 10 acceptance: attribution ENABLED costs ≤ 2% of a real
    tiny-model decode step on the steps it does NOT sample (the common
    case — the sampled step itself pays for its capture, which is the
    point of sampling). Denominator = the real slot_decode_step, the
    least favorable one."""
    spec, _ = tiny
    eng = _engine(tiny)
    sched = Scheduler(eng, chunk=8)
    sched.warmup()
    req = sched.submit([1, 9, 23], 200, _greedy(spec))
    times = []
    sched.step()  # prefill + first token
    for _ in range(30):
        t0 = time.perf_counter()
        sched.step()
        times.append(time.perf_counter() - t0)
    req.cancel()
    sched.step()
    sched.close()
    step_ms = sorted(times)[len(times) // 2] * 1e3

    PROFILER.sample_every = 1 << 30  # enabled; nothing actually samples
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        if PROFILER.sample_every:
            PROFILER.step_begin()
    per_step_ms = (time.perf_counter() - t0) / n * 1e3
    overhead = per_step_ms / step_ms
    assert overhead <= 0.02, (
        f"sampling guard costs {per_step_ms * 1e3:.2f} us/step = "
        f"{overhead * 100:.3f}% of a {step_ms:.2f} ms decode step")


# -- sampled attribution + capture ------------------------------------------


def test_sampled_steps_feed_device_time_without_breaking_serving(tiny):
    """--profile-sample N: every Nth working step runs under a short
    jax.profiler trace; serving output is unchanged and the profiler
    records the samples (per-entry attribution needs a device plane —
    present on TPU/GPU; CPU traces may carry host planes only, so the
    by_entry map is best-effort here and the SAMPLING machinery is what
    this pins)."""
    spec, _ = tiny
    eng = _engine(tiny)
    sched = Scheduler(eng, chunk=8)
    sched.warmup()
    PROFILER.sample_every = 3
    req = sched.submit([1, 9, 23, 54, 7], 6, _greedy(spec))
    while not req.finished.is_set():
        sched.step()
    toks = list(req.tokens(timeout=10.0))
    sched.close()
    assert len(toks) == 6
    # ingest runs on a short daemon thread (the scheduler thread must
    # get back to serving) — poll it in
    end = time.perf_counter() + 30.0
    while (PROFILER.sampled + PROFILER.sample_failures < 1
           and time.perf_counter() < end):
        time.sleep(0.02)
    assert PROFILER.sampled + PROFILER.sample_failures >= 1
    s = PROFILER.summary()
    assert s["sample_every"] == 3
    assert isinstance(s["by_entry"], dict)
    json.dumps(s)


def test_sync_stats_split_and_summary():
    """dlwire sync/compute attribution: SyncStats records one
    (collective ms, device ms, step wall ms) triple per sampled step;
    the share is window-sums (an idle step's ratio must not swamp the
    loaded ones), percentiles are nearest-rank, and an empty window
    reports n=0 with no invented numbers."""
    from distributed_llama_tpu.runtime.profiler import SyncStats

    s = SyncStats()
    assert s.summary() == {"n": 0}
    # three sampled steps: 25% / 50% / 0% collective
    s.record(2.0, 8.0, 9.0)
    s.record(4.0, 8.0, 9.5)
    s.record(0.0, 4.0)
    out = s.summary()
    assert out["n"] == 3
    assert out["sync_p50_ms"] == 2.0
    assert out["device_p50_ms"] == 8.0
    assert out["sync_share"] == round(6.0 / 20.0, 4)
    assert out["wall_p50_ms"] == 9.0  # 2 wall samples: nearest-rank
    # p50 rounds to the LOWER observed value (stats.percentile, no
    # interpolation — round(0.5) banker's-rounds to 0)
    json.dumps(out)

    # bounded window: old samples roll off
    s2 = SyncStats(window=4)
    for i in range(10):
        s2.record(1.0, 2.0, 3.0)
    assert s2.summary()["n"] == 4


def test_profiler_summary_carries_sync_block():
    """The `sync` half rides the device_time /stats block (and from
    there the dllama_step_sync_* /metrics families) in every state —
    empty (n=0) until a sampled step lands on a backend with a device
    plane."""
    s = PROFILER.summary()
    assert s["sync"] == {"n": 0}
    PROFILER.sync.record(1.5, 6.0, 7.0)
    s = PROFILER.summary()
    assert s["sync"]["n"] == 1 and s["sync"]["sync_share"] == 0.25
    json.dumps(s)
    PROFILER.reset()
    assert PROFILER.summary()["sync"] == {"n": 0}


def test_capture_writes_a_trace_and_refuses_concurrent(tmp_path):
    d = str(tmp_path / "cap")
    out = PROFILER.capture(d, ms=20)
    assert out["dir"] == d and os.path.isdir(d)
    assert PROFILER.captures == 1
    # the busy refusal: hold the slot, expect the structured error
    PROFILER._busy = True
    with pytest.raises(RuntimeError, match="busy"):
        PROFILER.capture(str(tmp_path / "cap2"), ms=10)
    PROFILER._busy = False


# -- build info -------------------------------------------------------------


def test_build_info_shape(tiny):
    eng = _engine(tiny, batch=1)
    b = build_info(eng)
    assert set(b) == {"version", "jax", "backend", "mesh"}
    assert b["mesh"] == "single" and b["backend"] == "cpu"
    assert b["version"] and b["jax"]
    assert build_info(None)["mesh"] == "single"
