"""Model downloader logic with a mocked transport (no network egress in CI
or this environment — ref download-model.py is similarly untestable live,
but the catalog/resume/atomic-rename logic doesn't need a network).
"""

import os
import urllib.error

import pytest

from distributed_llama_tpu.converters import download as dl


@pytest.fixture
def fake_transport(monkeypatch):
    """urlretrieve double: writes url-derived bytes to the temp path, and
    can be told to fail mid-flight."""
    calls = []
    fail_on = set()

    def fake_urlretrieve(url, dest, reporthook=None):
        calls.append(url)
        if url in fail_on:
            with open(dest, "wb") as f:
                f.write(b"partial")  # truncated temp file left behind
            raise urllib.error.URLError("boom")
        with open(dest, "wb") as f:
            f.write(b"DATA:" + url.encode())
        if reporthook:
            reporthook(256, 1024, 1 << 20)

    monkeypatch.setattr(dl.urllib.request, "urlretrieve", fake_urlretrieve)
    return calls, fail_on


def test_fetch_downloads_model_and_tokenizer(tmp_path, fake_transport):
    calls, _ = fake_transport
    m, t = dl.fetch_model("tinyllama", out_dir=str(tmp_path))
    assert os.path.exists(m) and os.path.exists(t)
    assert len(calls) == 2
    with open(m, "rb") as f:
        assert f.read().startswith(b"DATA:")
    # no stray temp files
    folder = os.path.dirname(m)
    assert not [p for p in os.listdir(folder) if p.endswith(".download")]


def test_fetch_is_idempotent(tmp_path, fake_transport):
    calls, _ = fake_transport
    dl.fetch_model("tinyllama", out_dir=str(tmp_path))
    n = len(calls)
    dl.fetch_model("tinyllama", out_dir=str(tmp_path))
    assert len(calls) == n  # existing files are not re-downloaded


def test_interrupted_download_leaves_no_final_file(tmp_path, fake_transport):
    """An interrupted transfer must not leave a truncated file at the FINAL
    path — the existence check would treat it as complete forever."""
    calls, fail_on = fake_transport
    key = "tinyllama_1_1b_3t_q40"
    fail_on.add(dl.CATALOG[key]["model"][0])
    with pytest.raises(urllib.error.URLError):
        dl.fetch_model("tinyllama", out_dir=str(tmp_path))
    folder = tmp_path / key
    finals = [p for p in os.listdir(folder) if p.endswith(".m")]
    assert finals == [], finals
    # retry after the failure is cleared succeeds and cleans up
    fail_on.clear()
    m, _ = dl.fetch_model("tinyllama", out_dir=str(tmp_path))
    assert os.path.exists(m)


def test_multipart_concatenation(tmp_path, fake_transport, monkeypatch):
    """Split archives download as parts and concatenate in order (the
    reference's multi-part 70B downloads, ref: download-model.py:40-52)."""
    entry = {"model": ["http://x/part0", "http://x/part1", "http://x/part2"],
             "tokenizer": "http://x/tok"}
    monkeypatch.setitem(dl.CATALOG, "fake_split", entry)
    m, t = dl.fetch_model("fake_split", out_dir=str(tmp_path))
    with open(m, "rb") as f:
        data = f.read()
    assert data == (b"DATA:http://x/part0" b"DATA:http://x/part1"
                    b"DATA:http://x/part2")
    folder = os.path.dirname(m)
    assert not [p for p in os.listdir(folder) if ".part" in p]  # parts removed


def test_unknown_name_and_list(tmp_path, capsys):
    with pytest.raises(KeyError):
        dl.fetch_model("nope", out_dir=str(tmp_path))
    dl.main(["--list"])
    out = capsys.readouterr().out
    assert "tinyllama_1_1b_3t_q40" in out
