"""Process-isolated replicas (runtime/replica_worker.py + the
RemoteReplicaHandle process supervision in runtime/router.py).

The chaos contract under test is the ISSUE 7 acceptance bar — the
strongest kill the repo can deliver, upgraded from "injected exception"
to a REAL ``SIGKILL -9`` of a live replica OS process mid-stream:

  * zero unstreamed request failures — a request whose worker dies
    before its first token fails over to a sibling replica within the
    retry budget and returns greedy tokens BIT-IDENTICAL to the
    single-engine oracle (the connection EOF surfaces as a structured
    RETRYABLE ``replica_lost`` frame, feeding the PR-6 failover
    machinery unchanged);
  * a request that already streamed tokens gets the structured
    NON-retryable frame (never a silent replay);
  * the process supervisor classifies the death (``signal:SIGKILL``),
    respawns the worker under backoff, and the replica is ROUTABLE
    again within the configured bound;
  * /stats counter totals carry across the respawn — never reset,
    never double-counted (the ``SupervisorStats`` contract, now across
    a process boundary);
  * a crash-looping worker (spawns that die young) trips the per-replica
    spawn breaker instead of respawning forever; ``reset_breaker`` is
    the operator half-open.

Every worker is a REAL subprocess running single-process CPU JAX over a
deterministic ``test_spec`` (same spec/seed as the in-test oracle, so
params are bit-identical across the process boundary) — the same
subprocess discipline as tests/test_cluster_chaos.py, so these run
wherever the cluster chaos tests do (the CI ``chaos`` job; the main
matrix ignores them).
"""

import os
import signal
import threading
import time

import pytest

jnp = pytest.importorskip("jax.numpy")

from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.models.params import load_params, random_tensors
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.runtime.replica_worker import (EXIT_WORKER_FAULT,
                                                          WorkerClient,
                                                          WorkerProc,
                                                          classify_exit)
from distributed_llama_tpu.runtime.resilience import EngineUnready
from distributed_llama_tpu.runtime.router import RemoteReplicaHandle, Router
from distributed_llama_tpu.runtime.scheduler import (PromptTooLong,
                                                     RequestError)
from distributed_llama_tpu.runtime.trace import TRACER
from distributed_llama_tpu.sampler import Sampler

SEQ = 64
SPEC_FIELDS = dict(dim=64, hidden_dim=128, n_layers=2, n_heads=4,
                   n_kv_heads=2, vocab_size=128, seq_len=SEQ)
SEED, SCALE = 3, 0.05

# the worker config every test ships: deterministic synthetic weights
# (same spec/seed/scale as the oracle below — bit-identical params in
# both processes), f32 so greedy parity compares bit-exactly. Workers
# run their own flight recorder (runtime/trace.py) so surviving
# requests ship worker-side spans back over RMSG_TRACE; with the
# parent's tracer off (every test but the SIGKILL one) the shipped
# frames are simply skipped
CFG = {"test_spec": SPEC_FIELDS, "seed": SEED, "scale": SCALE,
       "compute_dtype": "f32", "batch": 2,
       "serve": {"stall_timeout": 60.0},
       "trace": {"capacity": 2048}}

# the worker subprocess environment: CPU jax, plus the parent's XLA
# compilation cache so repeat spawns skip the compile cost
WORKER_ENV = {
    "JAX_PLATFORMS": "cpu",
    "JAX_COMPILATION_CACHE_DIR": os.path.join(
        os.path.expanduser("~"), ".cache", "dllama_tpu_xla"),
    "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "1.0",
}

SPAWN_TIMEOUT = 120.0   # worker startup bound (import + build + warmup)
# _wait's give-up ceiling. Nothing below asserts elapsed time against
# it: the chaos tests wait on OBSERVABLE monitor transitions (exit
# classified -> respawn counted -> routable) and this bound only
# decides when a wait that will never succeed stops burning CI time.
# A respawn is a full interpreter + jax import + engine build + warmup
# in a fresh subprocess, so the ceiling is generous by construction.
RESPAWN_BOUND = 180.0


@pytest.fixture(scope="module")
def oracle_bits():
    spec = ModelSpec(arch=ArchType.LLAMA, hidden_act=HiddenAct.SILU,
                     **SPEC_FIELDS)
    host = random_tensors(spec, seed=SEED, scale=SCALE)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    return spec, params


def _greedy():
    return Sampler(SPEC_FIELDS["vocab_size"], temperature=0.0, topp=0.9,
                   seed=1)


def _oracle(oracle_bits, prompt, max_tokens):
    spec, params = oracle_bits
    eng = Engine(spec, params, batch=1, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    return eng.generate(prompt, max_tokens, _greedy()).tokens


def _proc(rid, workdir, faults=""):
    return WorkerProc(rid, dict(CFG, fault_key=f"r{rid}"),
                      workdir=str(workdir), env=WORKER_ENV,
                      faults=faults or None)


def _handle(rid, workdir, faults="", **kw):
    kw.setdefault("poll_interval", 0.1)
    kw.setdefault("spawn_backoff_base", 0.05)
    kw.setdefault("spawn_timeout", SPAWN_TIMEOUT)
    kw.setdefault("respawn_timeout", SPAWN_TIMEOUT)
    return RemoteReplicaHandle(rid, proc=_proc(rid, workdir, faults), **kw)


def _wait(pred, timeout=RESPAWN_BOUND, poll=0.02):
    end = time.perf_counter() + timeout
    while time.perf_counter() < end:
        if pred():
            return True
        time.sleep(poll)
    return False


def _two_replica_router(mk, **router_kw):
    """Spawn two worker handles CONCURRENTLY (construction blocks on the
    port handshake — import + build + warmup; the shared compilation
    cache makes the second compile-free but not import-free), then hand
    Router prebuilt handles. Keeps the two-replica chaos tests inside
    the fast tier's time budget."""
    handles = [None, None]

    def build(i):
        handles[i] = mk(i)

    threads = [threading.Thread(target=build, args=(i,)) for i in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if not all(h is not None for h in handles):
        for h in handles:
            if h is not None:
                h.close()  # don't orphan the sibling that DID come up
        raise AssertionError("worker spawn failed")
    return Router(None, handle_factories=[lambda: handles[0],
                                          lambda: handles[1]], **router_kw)


# -- the framed protocol, one worker --------------------------------------


def test_worker_roundtrip_parity_refusals_and_admin_verbs(tmp_path,
                                                          oracle_bits):
    """One worker process over the framed codec: greedy tokens are
    bit-identical to the in-process oracle (the sampler spec rides the
    submit frame and is reconstructed worker-side), door refusals
    re-raise the SAME exception types the in-process supervisor uses,
    RMSG_REBUILD swaps the supervisor while counters carry, and a
    graceful shutdown is exit 0 / ``clean``."""
    proc = _proc(0, tmp_path)
    proc.spawn()
    try:
        port = proc.wait_ready(timeout=SPAWN_TIMEOUT)
        client = WorkerClient("127.0.0.1", port)
        h = client.ping()
        assert h is not None and h["ready"] and h["state"] == "ready"

        p = [1, 9, 23, 54, 7]
        rs = client.submit(p, 6, _greedy())
        assert list(rs.tokens(timeout=60.0)) == _oracle(oracle_bits, p, 6)
        assert rs.finish_reason == "length"
        # the HELLO ack cached the shape template (the handlers' slice)
        assert client.batch == 2 and client.seq_len == SEQ

        # door refusal types survive the wire
        with pytest.raises(PromptTooLong):
            client.submit(list(range(1, SEQ + 2)), 2, _greedy())

        # rolling-restart verb: fresh supervisor, counters carry
        before = client.stats_summary()
        assert before["requests_finished"] == 1
        assert client.rebuild(timeout=SPAWN_TIMEOUT)
        after = client.stats_summary()
        assert after["requests_finished"] == 1      # carried, not reset
        assert after["tokens_out"] == before["tokens_out"]
        rs = client.submit(p, 6, _greedy())          # and it still serves
        assert list(rs.tokens(timeout=60.0)) == _oracle(oracle_bits, p, 6)
        assert client.stats_summary()["requests_finished"] == 2

        assert client.shutdown()
        rc = proc.stop(timeout=20.0)
        assert rc == 0 and classify_exit(rc) == "clean"
    finally:
        proc.stop(timeout=10.0)


def test_worker_exit_fault_is_retryable_eof_pre_token(tmp_path):
    """The ``worker_exit`` site (the in-process SIGKILL/OOM stand-in):
    armed with key=r0 in the worker's OWN environment, the worker
    os._exits immediately before its first token frame — the client
    sees a mid-request EOF with ZERO tokens streamed and raises the
    structured RETRYABLE ``replica_lost`` frame (exactly what the
    router's failover machinery consumes), and the corpse classifies as
    ``fault_exit``."""
    proc = _proc(0, tmp_path, faults="worker_exit:key=r0")
    proc.spawn()
    try:
        port = proc.wait_ready(timeout=SPAWN_TIMEOUT)
        client = WorkerClient("127.0.0.1", port)
        rs = client.submit([1, 9, 23], 4, _greedy())
        got = []
        with pytest.raises(RequestError) as ei:
            for t in rs.tokens(timeout=60.0):
                got.append(t)
        assert got == []                      # pre-first-token, always
        assert ei.value.code == "replica_lost"
        assert ei.value.retryable is True
        assert _wait(lambda: proc.poll() is not None, 30.0)
        assert proc.poll() == EXIT_WORKER_FAULT
        assert classify_exit(proc.poll()) == "fault_exit"
    finally:
        proc.stop(timeout=10.0)


# -- the acceptance chaos test: real SIGKILL mid-stream --------------------


def test_sigkill_mid_stream_zero_unstreamed_failures_and_respawn(
        tmp_path, oracle_bits):
    """ISSUE 7 acceptance: ``kill -9`` a live replica worker process
    while it serves a mid-stream request AND holds a not-yet-streamed
    one. The streamed request gets the structured NON-retryable frame
    (partial output is never silently replayed); the unstreamed one
    fails over to the sibling replica and returns BIT-IDENTICAL greedy
    tokens; the service stays ready throughout; and the supervisor
    classifies the SIGKILL and respawns the worker to routable within
    the bound.

    ISSUE 9 rides the same kill: the flight recorder must link the
    casualty span, the classified exit, and the bit-identical sibling
    retry as ONE cross-process timeline (the trace id travels in the
    submit frame; the parent records the casualty itself because a
    SIGKILLed worker can never ship its span)."""
    TRACER.configure(capacity=8192)
    # worker-side slow_step paces decode (80 ms/step) so the kill
    # provably lands while streams are in flight
    router = _two_replica_router(
        lambda i: _handle(i, tmp_path, faults="slow_step:times=0;ms=80"),
        policy="round_robin", retry_budget=1)
    h0, h1 = router.replicas
    p = [1, 9, 23, 54, 7]
    want6 = _oracle(oracle_bits, p, 6)
    ready_gaps = []
    sampling = threading.Event()
    sampling.set()

    def sample_ready():
        while sampling.is_set():
            if not router.ready:
                ready_gaps.append(time.perf_counter())
            time.sleep(0.005)

    try:
        samp = threading.Thread(target=sample_ready, daemon=True)
        samp.start()
        # round_robin placement is deterministic: A -> r0, B -> r1,
        # C -> r0
        req_a = router.submit(p, 6, _greedy())
        req_b = router.submit(p, 6, _greedy())
        it_a = req_a.tokens(timeout=120.0)
        got_a = [next(it_a)]              # A is LIVE mid-stream on r0...
        # ...and C joins r0 only NOW, after A's first token: its own
        # first token is at least one paced prefill + one paced decode
        # step away (>= 160 ms), so the kill provably lands before C
        # streams anything
        req_c = router.submit(p, 6, _greedy())
        assert (req_a.replica_id, req_b.replica_id,
                req_c.replica_id) == (0, 1, 0)
        os.kill(h0._proc.proc.pid, signal.SIGKILL)

        # A: already streamed -> structured NON-retryable frame
        with pytest.raises(RequestError) as ei:
            for t in it_a:
                got_a.append(t)
        assert ei.value.retryable is False
        assert "already streamed" in str(ei.value)
        assert len(got_a) >= 1
        assert got_a == want6[:len(got_a)]  # the partial stream was real

        # C: zero tokens streamed -> bounded failover to r1, parity
        got_c = list(req_c.tokens(timeout=120.0))
        assert got_c == want6, "failover lost greedy parity"
        assert req_c.retries == 1 and req_c.replica_id == 1

        # B (on the surviving replica) never noticed
        assert list(req_b.tokens(timeout=120.0)) == want6

        # supervised respawn, event-driven: wait on each observable state
        # transition of the monitor in order — exit CLASSIFIED, respawn
        # COUNTED, worker routable. RESPAWN_BOUND is only _wait's
        # give-up ceiling; no assertion does wall-clock arithmetic.
        assert _wait(lambda: h0.proc_stats.exit_classes
                     .get("signal:SIGKILL", 0) >= 1), \
            "monitor never classified the SIGKILL"
        assert _wait(lambda: h0.proc_stats.respawns >= 1), \
            "monitor never completed a respawn"
        assert _wait(lambda: h0.ready), \
            "respawned worker never became routable"
        ps = h0.proc_stats.summary()
        assert ps["exit_classes"].get("signal:SIGKILL") == 1
        assert ps["respawns"] == 1
        assert ps["respawn_p50_ms"] is not None

        # the respawned worker SERVES (fresh process, same weights)
        req_d = router.submit(p, 4, _greedy())
        assert list(req_d.tokens(timeout=120.0)) == want6[:4]

        # the single-replica outage was invisible at the service level
        assert not ready_gaps, f"router went unready at {ready_gaps}"

        # -- the flight-recorder story of the kill (ISSUE 9) ----------
        # C's span: ONE trace id links route->r0, the replica_lost
        # casualty (zero tokens), the failover, and the route->r1 retry
        span_c = TRACER.by_id(req_c.trace_id)
        kinds_c = [e["kind"] for e in span_c]
        routes = [e for e in span_c if e["kind"] == "route"]
        assert [r["replica"] for r in routes] == [0, 1]
        err_c = next(e for e in span_c if e["kind"] == "error")
        assert err_c["code"] == "replica_lost" and err_c["n_out"] == 0
        fo = next(e for e in span_c if e["kind"] == "failover")
        assert fo["replica"] == 0 and fo["attempt"] == 1
        assert (kinds_c.index("error") < kinds_c.index("failover")
                < len(kinds_c) - kinds_c[::-1].index("route"))
        # A's span: the mid-stream casualty — it streamed (client-side
        # first_token), then lost its worker mid-request
        span_a = TRACER.by_id(req_a.trace_id)
        assert any(e["kind"] == "first_token" for e in span_a)
        err_a = next(e for e in span_a if e["kind"] == "error"
                     and e["code"] == "replica_lost")
        assert err_a["n_out"] >= 1
        # the kill itself, classified, on the same timeline
        exits = [e for e in TRACER.recent(0) if e["kind"] == "worker_exit"]
        assert exits and exits[0]["replica"] == 0
        assert exits[0]["cls"] == "signal:SIGKILL"
        # B survived on r1: its worker shipped its span over RMSG_TRACE
        # — worker-side events (origin worker@...) merged onto the
        # parent timeline, the cross-process half of the contract
        span_b = TRACER.by_id(req_b.trace_id)
        worker_evs = [e for e in span_b if str(e.get("origin",
                                                     "")).startswith("worker@")]
        assert any(e["kind"] == "finish" for e in worker_evs)
        assert any(e["kind"] == "admit" for e in worker_evs)

        assert router.stats.midstream_failures == 1
        assert router.stats.retries == 1
        assert router.stats.failovers_ok == 1

        # -- /metrics over the PROCESS tier (the third serving tier of
        # the ISSUE 9 acceptance bar): the real HTTP handler over this
        # very router serves valid Prometheus text with the per-replica
        # process series — including the classified SIGKILL
        import http.client

        from distributed_llama_tpu.apps.api_server import (ApiState,
                                                           make_handler)
        from http.server import ThreadingHTTPServer

        state = ApiState(None, None, None, model_name="procs",
                         serve_batch=2, replica_procs=2)
        state._scheduler = router
        srv = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            conn = http.client.HTTPConnection(*srv.server_address,
                                              timeout=60)
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            body = resp.read().decode()
            assert resp.status == 200
            assert resp.getheader("Content-Type").startswith("text/plain")
            assert 'dllama_up{model="procs",mode="router"} 1' in body
            assert ('dllama_replica_proc_exit_class_total'
                    '{replica="0",class="signal:SIGKILL"} 1') in body
            assert 'dllama_replica_up{replica="1"} 1' in body
            assert "dllama_router_retries_total 1" in body
            conn.close()
        finally:
            srv.shutdown()
    finally:
        sampling.clear()
        router.close()
        TRACER.reset()


# -- /stats aggregation across a respawn (satellite) -----------------------


def test_stats_totals_carry_across_respawn_no_reset_no_double_count(
        tmp_path, oracle_bits):
    """Counter totals in the router's /stats aggregation must behave
    across a worker respawn exactly like SupervisorStats does across an
    engine rebuild: carried, never reset, never double-counted. The
    parent folds the dead process's last-polled counters into a carry;
    with the monitor given one quiet poll interval before the kill, the
    fold is exact."""
    router = Router(None, policy="least_loaded", retry_budget=1,
                    handle_factories=[lambda: _handle(0, tmp_path)])
    h0 = router.replicas[0]
    p = [2, 40, 77, 5]
    try:
        for _ in range(2):
            req = router.submit(p, 3, _greedy())
            assert list(req.tokens(timeout=120.0)) == _oracle(
                oracle_bits, p, 3)
        # let the monitor's PONG poll capture the finished counters so
        # the carry across the kill is exact, not a lower bound
        assert _wait(lambda: h0._last_counters["requests_finished"] == 2,
                     10.0)
        s1 = router.summary()
        assert s1["requests_finished"] == 2
        assert s1["tokens_out"] == 6

        os.kill(h0._proc.proc.pid, signal.SIGKILL)
        assert _wait(lambda: h0.proc_stats.respawns == 1, RESPAWN_BOUND)
        # mid-restart reads never went backwards or forward-jumped
        s2 = router.summary()
        assert s2["requests_finished"] == 2      # carried, not reset
        assert s2["tokens_out"] == 6             # and not double-counted

        assert _wait(lambda: h0.ready, RESPAWN_BOUND)
        req = router.submit(p, 3, _greedy())
        assert list(req.tokens(timeout=120.0)) == _oracle(
            oracle_bits, p, 3)
        s3 = router.summary()
        assert s3["requests_finished"] == 3      # old 2 + new 1
        assert s3["tokens_out"] == 9
        reps = s3["replicas"]
        assert reps[0]["proc"]["mode"] == "spawn"
        assert reps[0]["proc"]["exit_classes"].get("signal:SIGKILL") == 1
    finally:
        router.close()


# -- spawn breaker on a crash loop ----------------------------------------


def test_crash_loop_trips_spawn_breaker_and_reset_recovers(tmp_path):
    """A worker whose respawns keep dying young (here: config file
    corrupted after a healthy start -> every respawn is a fast exit 2
    ``config_error``) must trip the per-replica spawn breaker instead of
    respawning forever; ``reset_breaker`` after restoring the config is
    the operator half-open that resumes supervision."""
    h0 = _handle(0, tmp_path, min_uptime=5.0, spawn_breaker=3,
                 spawn_backoff_max=0.2)
    try:
        assert h0.ready
        good = open(h0._proc.config_path).read()
        with open(h0._proc.config_path, "w") as f:
            f.write("{not json")
        os.kill(h0._proc.proc.pid, signal.SIGKILL)
        assert _wait(lambda: h0.state == "broken", RESPAWN_BOUND), \
            f"breaker never tripped (state {h0.state})"
        assert not h0.ready
        with pytest.raises(EngineUnready):
            h0.submit([1, 2, 3], 2, _greedy())
        assert h0.proc_stats.spawn_failures >= 1
        assert h0.proc_stats.exit_classes.get("config_error", 0) >= 1

        # operator half-open: fix the config, reset, supervision resumes
        with open(h0._proc.config_path, "w") as f:
            f.write(good)
        h0.reset_breaker()
        assert _wait(lambda: h0.ready, RESPAWN_BOUND), \
            "reset_breaker did not resume respawning"
        rs = h0.submit([1, 9, 23], 2, _greedy())
        assert len(list(rs.tokens(timeout=60.0))) == 2
    finally:
        h0.close()


# -- shadow prefix index placement (process-mode cache awareness) ----------


def test_shadow_index_routes_cache_aware_and_clears_on_respawn(
        tmp_path, oracle_bits):
    """Cache-aware placement across the process boundary: the router's
    shadow radix index records what it ROUTED (no RPC on the hot path),
    so a repeat prompt is placed on the replica that already served its
    prefix; a worker death clears that replica's shadow (the respawned
    process holds an empty real tree)."""
    cfg_pc = dict(CFG, prefix_cache=True, prefix_blocks=32,
                  prefix_block_len=4)

    def mk(i):
        proc = WorkerProc(i, dict(cfg_pc, fault_key=f"r{i}"),
                          workdir=str(tmp_path), env=WORKER_ENV)
        return RemoteReplicaHandle(i, proc=proc, block_len=4,
                                   poll_interval=0.1,
                                   spawn_backoff_base=0.05,
                                   spawn_timeout=SPAWN_TIMEOUT,
                                   respawn_timeout=SPAWN_TIMEOUT)

    router = _two_replica_router(mk, policy="cache_aware", retry_budget=1)
    h0 = router.replicas[0]
    p = [1, 9, 23, 54, 7, 11, 40, 3, 15]   # two whole 4-token blocks
    try:
        want = _oracle(oracle_bits, p, 3)
        r1 = router.submit(p, 3, _greedy())
        assert list(r1.tokens(timeout=120.0)) == want
        assert r1.replica_id == 0           # idle tie-break: lowest id
        assert h0.match_len(p) >= 4         # the shadow recorded it
        # repeat prompt: placed by SHADOW match, not fallback
        r2 = router.submit(p, 3, _greedy())
        assert list(r2.tokens(timeout=120.0)) == want
        assert r2.replica_id == 0
        assert router.stats.routed_cache_hit >= 1

        os.kill(h0._proc.proc.pid, signal.SIGKILL)
        assert _wait(lambda: h0.proc_stats.respawns == 1, RESPAWN_BOUND)
        assert h0.match_len(p) == 0         # shadow cleared with the corpse
    finally:
        router.close()


# -- /admin/profile over the process tier (ISSUE 10) ------------------------


def test_admin_profile_guarded_and_rmsg_profile_roundtrips(tmp_path,
                                                           oracle_bits,
                                                           monkeypatch):
    """The chaos-job half of the ISSUE 10 capture satellite: the
    RMSG_PROFILE verb round-trips to a REAL worker process — the capture
    lands in that worker's own per-worker dir — and, over HTTP on the
    process tier, POST /admin/profile is admin-guarded off-loopback
    exactly like every other /admin/* verb (403 bare, 200 + per-worker
    dirs with the --admin-token bearer)."""
    import http.client
    import json as _json
    from http.server import ThreadingHTTPServer

    import distributed_llama_tpu.apps.api_server as api_mod
    from distributed_llama_tpu.apps.api_server import (ApiState,
                                                       make_handler)

    cfg = dict(CFG, profile_dir=str(tmp_path / "prof"), fault_key="r0")
    proc = WorkerProc(0, cfg, workdir=str(tmp_path), env=WORKER_ENV)
    h0 = RemoteReplicaHandle(0, proc=proc, poll_interval=0.1,
                             spawn_backoff_base=0.05,
                             spawn_timeout=SPAWN_TIMEOUT,
                             respawn_timeout=SPAWN_TIMEOUT)
    router = Router(None, policy="least_loaded", retry_budget=1,
                    handle_factories=[lambda: h0])
    try:
        # the verb itself, straight through the framed codec: the 200
        # (RMSG_OK) is synchronous with the capture, so the per-worker
        # dir exists the moment the reply lands
        out = h0.profile(40)
        assert out is not None, "RMSG_PROFILE failed"
        want_prefix = os.path.join(str(tmp_path), "prof", "worker-r0")
        assert out["dir"].startswith(want_prefix), out
        assert os.path.isdir(out["dir"])

        # HTTP relay + the off-loopback guard
        state = ApiState(None, None, None, model_name="procs",
                         serve_batch=2, replica_procs=1)
        state._scheduler = router
        srv = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            def post(headers=None):
                conn = http.client.HTTPConnection(*srv.server_address,
                                                  timeout=120)
                conn.request("POST", "/admin/profile?ms=40", b"{}",
                             {"Content-Type": "application/json",
                              **(headers or {})})
                resp = conn.getresponse()
                return resp.status, _json.loads(resp.read())

            monkeypatch.setattr(api_mod, "_is_loopback", lambda a: False)
            status, body = post()
            assert status == 403 and "admin" in body["error"]
            state.admin_token = "tok-prof"
            status, body = post({"Authorization": "Bearer tok-prof"})
            assert status == 200, body
            w = body["workers"]["r0"]
            assert w is not None and w["dir"].startswith(want_prefix)
            assert os.path.isdir(w["dir"])
            assert w["dir"] != out["dir"]  # a fresh capture, not a replay
        finally:
            srv.shutdown()
    finally:
        router.close()
