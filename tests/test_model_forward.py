"""Golden forward tests: JAX model vs the numpy reference-math oracle.

Plays the role of the reference's llama2/grok1 golden-block tests
(ref: src/llama2-tasks-test.cpp, grok1-tasks-test.cpp) but checks every arch
end-to-end over several positions instead of one hard-coded block.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.models.params import load_params, random_tensors
from distributed_llama_tpu.models.transformer import KVCache, forward

from reference_oracle import Oracle


def make_spec(arch, **kw):
    base = dict(
        arch=arch, dim=64, hidden_dim=96, n_layers=2, n_heads=4, n_kv_heads=2,
        vocab_size=128, seq_len=16,
        hidden_act=HiddenAct.GELU if arch == ArchType.GROK1 else HiddenAct.SILU,
        rope_theta=10000.0,
    )
    if arch in (ArchType.MIXTRAL, ArchType.GROK1):
        base.update(n_experts=4, n_active_experts=2)
    base.update(kw)
    return ModelSpec(**base)


def dense_weights(spec, seed=0):
    host = random_tensors(spec, seed=seed, scale=0.05)
    return host, {k: v.to_f32() for k, v in host.items()}


@pytest.mark.parametrize("arch", [ArchType.LLAMA, ArchType.MIXTRAL, ArchType.GROK1])
def test_forward_matches_oracle(arch):
    spec = make_spec(arch)
    host, w = dense_weights(spec)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    oracle = Oracle(spec, w)

    cache = KVCache.create(spec, batch=1)
    tokens = [3, 17, 42, 7, 99]
    for pos, tok in enumerate(tokens):
        want = oracle.step(tok, pos)
        got, cache = forward(
            params, spec, jnp.array([[tok]], jnp.int32), jnp.int32(pos), cache)
        got = np.asarray(got).reshape(-1)
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-4)


@pytest.mark.parametrize("arch", [ArchType.LLAMA, ArchType.MIXTRAL])
def test_prefill_equals_tokenwise_decode(arch):
    """Chunked prefill (T>1) must produce the same cache/logits as feeding
    tokens one at a time (the reference only has the token-wise path)."""
    spec = make_spec(arch)
    host, _ = dense_weights(spec, seed=1)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)

    toks = np.array([[5, 9, 2, 77, 31]], np.int32)

    cache_a = KVCache.create(spec, batch=1)
    logits_a, cache_a = forward(params, spec, jnp.asarray(toks), jnp.int32(0), cache_a)

    cache_b = KVCache.create(spec, batch=1)
    for i in range(toks.shape[1]):
        logits_b, cache_b = forward(
            params, spec, jnp.asarray(toks[:, i:i + 1]), jnp.int32(i), cache_b)

    # identical math, different f32 reduction order (batched vs per-token
    # einsum), compounding across layers — absolute tolerance on O(1) values
    np.testing.assert_allclose(np.asarray(logits_a), np.asarray(logits_b), rtol=0, atol=1e-3)
    np.testing.assert_allclose(np.asarray(cache_a.k), np.asarray(cache_b.k), rtol=0, atol=1e-3)


def test_q40_params_close_to_dense():
    """Q40 weight path: same forward within quantization noise."""
    spec = make_spec(ArchType.LLAMA)
    host, _ = dense_weights(spec, seed=2)
    dense = load_params(spec, host, mode="dense", dtype=jnp.float32)
    q40 = load_params(spec, host, mode="q40")

    cache1 = KVCache.create(spec, batch=1)
    cache2 = KVCache.create(spec, batch=1)
    tok = jnp.array([[11]], jnp.int32)
    l_dense, _ = forward(dense, spec, tok, jnp.int32(0), cache1)
    l_q40, _ = forward(q40, spec, tok, jnp.int32(0), cache2)
    # small model, small weights: quantization error stays moderate
    err = np.abs(np.asarray(l_dense) - np.asarray(l_q40)).max()
    assert err < 0.5
    assert np.corrcoef(np.asarray(l_dense).ravel(), np.asarray(l_q40).ravel())[0, 1] > 0.98


def test_moe_decode_fused_expert_path_matches_xla():
    """MoE decode with the expert-indexed Pallas kernels (interpret mode)
    must match the plain XLA gather path token for token."""
    spec = make_spec(ArchType.MIXTRAL)
    host, _ = dense_weights(spec, seed=4)
    params = load_params(spec, host, mode="q40")

    cache_a = KVCache.create(spec, batch=1)
    cache_b = KVCache.create(spec, batch=1)
    for pos, tok in enumerate([3, 17, 42, 7]):
        t = jnp.array([[tok]], jnp.int32)
        a, cache_a = forward(params, spec, t, jnp.int32(pos), cache_a)
        b, cache_b = forward(params, spec, t, jnp.int32(pos), cache_b,
                             use_pallas=True, pallas_interpret=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=2e-4)


def test_activation_q80_path_runs():
    """Q80 activation round-trip (wire-compression parity feature) stays close
    to the f32 path (ref quantizes activations between all steps)."""
    spec = make_spec(ArchType.LLAMA)
    host, _ = dense_weights(spec, seed=3)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    tok = jnp.array([[21]], jnp.int32)
    a, _ = forward(params, spec, tok, jnp.int32(0), KVCache.create(spec, 1))
    b, _ = forward(params, spec, tok, jnp.int32(0), KVCache.create(spec, 1), activation_q80=True)
    assert np.corrcoef(np.asarray(a).ravel(), np.asarray(b).ravel())[0, 1] > 0.99
