"""The closed batch-knee loop (ISSUE 11): calibration artifact contract,
startup auto-sizing (``--serve-batch auto`` / ``--prefix-blocks auto``
via runtime/profiler.resolve_auto_shape), and the SLO-aware self-tuning
admission policy (runtime/scheduler.AdmissionPolicy).

The contracts under test:

  * auto-sizing NEVER exceeds what the HBM ledger says fits
    (headroom-capped), never exceeds the calibrated knee without an SLO
    budget that affords it (knee-capped / slo-curve-raised), and refuses
    a ledger-less engine with a clear error instead of crashing;
  * the adaptive chunk width converges to the ladder floor under a
    synthetic slow-step fault (the ``slow_step`` site) and recovers;
  * greedy outputs are BIT-IDENTICAL adaptive-vs-static (chunk
    boundaries must never change tokens — the scheduler parity contract
    extended to a moving width);
  * an adaptive run mints ZERO post-warmup compile keys (warmup warms
    the whole ladder, so ``--freeze-compiles`` stays green while the
    width moves);
  * the CLI sentinels and SLO flags validate at parse time (dead-flag
    rules), before any model load.
"""

import os
import sys

import pytest

jnp = pytest.importorskip("jax.numpy")

from distributed_llama_tpu.apps import dllama
from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.models.params import load_params, random_tensors
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.runtime.faults import FAULTS
from distributed_llama_tpu.runtime.profiler import (COMPILES, load_autotune,
                                                    resolve_auto_shape,
                                                    validate_autotune)
from distributed_llama_tpu.runtime.scheduler import (AdmissionPolicy,
                                                     Scheduler, chunk_ladder)
from distributed_llama_tpu.sampler import Sampler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import dlprof  # noqa: E402

SEQ = 64


@pytest.fixture(scope="module")
def tiny():
    spec = ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=128,
                     seq_len=SEQ, hidden_act=HiddenAct.SILU)
    host = random_tensors(spec, seed=3, scale=0.05)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    return spec, params


def _greedy(spec):
    return Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=1)


def _artifact(knee_rows=4, curve=None):
    return {"kind": "dllama-autotune", "version": 1, "model": "tiny",
            "backend": "cpu", "created_unix": 0.0,
            "decode_curve": curve if curve is not None else [],
            "knee": {"knee_rows": knee_rows,
                     "method": "marginal_throughput"}}


# -- artifact contract ------------------------------------------------------


def test_validators_agree_and_loader_refuses_garbage(tmp_path):
    """The canonical validator (runtime/profiler — what --serve-batch
    auto trusts) and dlprof's standalone mirror must accept and reject
    the SAME artifacts (dlprof duplicates on purpose: it runs with no
    repo on the path)."""
    import json

    good = _artifact()
    bad_version = dict(good, version=99)
    bad_kind = dict(good, kind="bogus")
    kneeless = dict(good, knee={})
    for art, ok in ((good, True), (bad_version, False), (bad_kind, False),
                    (kneeless, False)):
        assert (not validate_autotune(art)) is ok, art
        assert (not dlprof.validate_autotune(art)) is ok, art
    p = tmp_path / "AUTOTUNE.json"
    p.write_text(json.dumps(bad_version))
    with pytest.raises(ValueError, match="version"):
        load_autotune(str(p))
    p.write_text(json.dumps(good))
    assert load_autotune(str(p))["knee"]["knee_rows"] == 4


def test_committed_artifact_validates():
    """The committed AUTOTUNE.json (the CPU-tiny calibration this PR
    ships) must satisfy the loader contract its consumers trust."""
    art = load_autotune(os.path.join(REPO, "AUTOTUNE.json"))
    assert art["backend"] == "cpu" and art["model"] == "tiny"
    assert art["knee"]["knee_rows"] >= 1
    assert len(art["decode_curve"]) >= 5  # the committed grid is 2..128
    assert art["prefill_ms_by_width"]  # the adaptive ladder was measured


# -- auto-sizing ------------------------------------------------------------


def test_auto_batch_headroom_capped(tiny):
    """`--serve-batch auto` never exceeds slots_addable: with a fake
    device limit worth 5 slots, a knee of 32 resolves to 5."""
    spec, params = tiny
    eng = Engine(spec, params, batch=1, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    per_slot = int(sum(x.nbytes for x in
                       __import__("jax").tree_util.tree_leaves(eng.cache)))
    dec = resolve_auto_shape(
        eng, serve_batch="auto", autotune=_artifact(knee_rows=32),
        device_stats={"bytes_in_use": 0, "bytes_limit": 5 * per_slot})
    assert dec["serve_batch"] == 5
    assert dec["serve_batch_basis"] == "hbm_cap"
    assert dec["inputs"]["slots_addable"] == 5
    # replicas split the same headroom
    dec2 = resolve_auto_shape(
        eng, serve_batch="auto", replicas=2,
        autotune=_artifact(knee_rows=32),
        device_stats={"bytes_in_use": 0, "bytes_limit": 5 * per_slot})
    assert dec2["serve_batch"] == 2


def test_auto_batch_knee_capped(tiny):
    """With ample headroom the calibrated knee is the cap; without an
    artifact the conservative default heuristic applies."""
    spec, params = tiny
    eng = Engine(spec, params, batch=1, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    dec = resolve_auto_shape(
        eng, serve_batch="auto", autotune=_artifact(knee_rows=4),
        device_stats={"bytes_in_use": 0, "bytes_limit": 1 << 40})
    assert dec["serve_batch"] == 4
    assert dec["serve_batch_basis"] == "autotune"
    dec2 = resolve_auto_shape(eng, serve_batch="auto", autotune=None,
                              device_stats=None)
    from distributed_llama_tpu.runtime.profiler import DEFAULT_KNEE_ROWS

    assert dec2["serve_batch"] == DEFAULT_KNEE_ROWS
    assert dec2["serve_batch_basis"] == "default_heuristic"


def test_auto_batch_slo_curve_raises_target(tiny):
    """An ITL SLO budget can afford capacity past the knee: with the
    curve showing batch 16 still under 0.2 x SLO, the target rises to
    16 — and a static serve_batch passes through untouched."""
    spec, params = tiny
    eng = Engine(spec, params, batch=1, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    curve = [{"rows": 4, "p50_ms": 10.0}, {"rows": 8, "p50_ms": 11.0},
             {"rows": 16, "p50_ms": 14.0}, {"rows": 32, "p50_ms": 25.0}]
    dec = resolve_auto_shape(
        eng, serve_batch="auto", slo_itl_ms=80.0,
        autotune=_artifact(knee_rows=8, curve=curve), device_stats=None)
    assert dec["serve_batch"] == 16  # 14 ms <= 0.2*80; 25 ms is not
    assert dec["serve_batch_basis"] == "slo_curve"
    assert dec["inputs"]["rows_under_itl_slo"] == 16
    static = resolve_auto_shape(
        eng, serve_batch=6, slo_itl_ms=80.0,
        autotune=_artifact(knee_rows=8, curve=curve), device_stats=None)
    assert static["serve_batch"] == 6
    assert static["serve_batch_basis"] == "static"


def test_auto_prefix_blocks_capped(tiny):
    """`--prefix-blocks auto`: the 2xBxcontext target, capped at HALF
    the blocks the free HBM could hold."""
    spec, params = tiny
    eng = Engine(spec, params, batch=1, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    bl = 16
    per_block = (2 * spec.n_layers * spec.n_kv_heads * bl
                 * spec.head_size * 4)
    dec = resolve_auto_shape(
        eng, serve_batch=2, prefix_blocks="auto", prefix_block_len=bl,
        autotune=_artifact(), device_stats={
            "bytes_in_use": 0, "bytes_limit": 8 * per_block})
    assert dec["prefix_blocks"] == 4  # 8 addable // 2
    assert dec["prefix_blocks_basis"] == "hbm_cap"
    dec2 = resolve_auto_shape(eng, serve_batch=2, prefix_blocks="auto",
                              prefix_block_len=bl, device_stats=None)
    assert dec2["prefix_blocks"] == 2 * 2 * SEQ // bl  # context heuristic
    assert dec2["prefix_blocks_basis"] == "context_heuristic"


def test_auto_refuses_ledgerless_engine():
    """A weightless front-door template (the process tier's parent)
    cannot be auto-sized: a clear ValueError, not a crash mid-build."""
    from distributed_llama_tpu.apps.dllama import FrontDoorTemplate

    class _Spec:
        seq_len = 64

    with pytest.raises(ValueError, match="ledger-capable"):
        resolve_auto_shape(FrontDoorTemplate(_Spec()), serve_batch="auto")


# -- the SLO-aware admission policy -----------------------------------------


def test_chunk_ladder_shape():
    assert chunk_ladder(32) == [32, 16, 8, 4]
    assert chunk_ladder(8) == [8, 4, 2, 1]
    assert chunk_ladder(2) == [2, 1]
    assert chunk_ladder(1) == [1]


def test_admission_policy_unit():
    """Shrink on ITL pressure (decode + prefill present), widen when
    decode idles or ITL is comfortable, cooldown-gated, ladder-bounded."""
    p = AdmissionPolicy(32, slo_itl_ms=10.0, cooldown=2)
    assert p.width == 32
    # pressure: EWMA above 0.85 * 10 with mixed work -> shrink one rung
    p.observe_step(20.0, decode_rows=2, prefill_rows=1)
    assert p.width == 16 and p.shrinks == 1
    # cooldown: the very next pressured step must NOT shrink again
    p.observe_step(20.0, decode_rows=2, prefill_rows=1)
    assert p.width == 16
    p.observe_step(20.0, decode_rows=2, prefill_rows=1)
    assert p.width == 8 and p.shrinks == 2
    # floor: pressure can never leave the ladder
    for _ in range(10):
        p.observe_step(50.0, decode_rows=2, prefill_rows=1)
    assert p.width == chunk_ladder(32)[-1]
    # recovery: comfortable ITL (< 0.5 * SLO EWMA) widens back up
    for _ in range(40):
        p.observe_step(1.0, decode_rows=2, prefill_rows=0)
    assert p.width == 32 and p.widens >= 3
    # pure-prefill iterations (decode idle) widen even with no samples
    p2 = AdmissionPolicy(32, slo_itl_ms=10.0, cooldown=1)
    p2._rung = 2
    p2.observe_step(30.0, decode_rows=0, prefill_rows=3)
    assert p2.width == 16 and p2.widens == 1
    # TTFT pressure with ITL headroom widens; without headroom it must
    # not (the ITL SLO wins the conflict)
    p3 = AdmissionPolicy(32, slo_ttft_ms=100.0, slo_itl_ms=10.0,
                         cooldown=1)
    p3._rung = 1
    p3.observe_ttft(95.0)
    p3.observe_step(6.0, decode_rows=2, prefill_rows=1)  # itl ewma 6.0
    assert p3.width == 32 and p3.widens == 1
    p3._rung = 1
    p3.itl_ewma_ms = 9.0  # near its own SLO: TTFT pressure is blocked
    p3.observe_step(9.0, decode_rows=2, prefill_rows=0)
    assert p3.width == 16


def test_adaptive_chunk_converges_under_slow_steps(tiny):
    """The acceptance shape: a synthetic slow-step fault (the
    ``slow_step`` site) drags every working step over the ITL SLO while
    prompts keep prefilling — the policy must walk the width down to the
    ladder floor (and the run must still produce correct tokens)."""
    spec, params = tiny
    eng = Engine(spec, params, batch=2, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    sched = Scheduler(eng, chunk=16, slo_itl_ms=30.0)
    sched.warmup()
    floor = sched.admission.ladder[-1]
    FAULTS.arm("slow_step", times=0, ms=40.0)  # every step > the SLO
    try:
        # one decode-heavy stream plus a SUPPLY of long prompts cycling
        # through the second slot: prefill_rows stays > 0 for many mixed
        # iterations — the composition the shrink rule requires — long
        # enough to walk the whole ladder down
        reqs = [sched.submit([1, 9, 23, 54], 24, _greedy(spec))]
        reqs += [sched.submit(list(range(1, 49)), 2, _greedy(spec))
                 for _ in range(3)]
        min_width = sched.admission.width
        for _ in range(800):
            if all(r.finished.is_set() for r in reqs):
                break
            sched.step()
            min_width = min(min_width, sched.admission.width)
        assert all(r.finished.is_set() for r in reqs)
    finally:
        FAULTS.clear()
        sched.close()
    adm = sched.stats.summary()["admission"]
    # the width walked the WHOLE ladder down while the fault held every
    # mixed step over the SLO (once decode idles at the trace tail, the
    # policy legitimately widens back — that recovery is also asserted)
    assert min_width == floor, (min_width, adm)
    assert adm["shrinks"] >= len(sched.admission.ladder) - 1
    assert adm["widens"] >= 1, adm
    assert adm["itl_ewma_ms"] > 30.0  # the signal it converged on


def test_greedy_parity_adaptive_vs_static(tiny):
    """Greedy outputs must be BIT-IDENTICAL whether the chunk width is
    pinned or adapting mid-run (an impossibly tight ITL SLO forces
    transitions): chunk boundaries never change tokens."""
    spec, params = tiny
    prompts = [[1, 9, 23, 54, 7, 88, 101, 5, 61, 17, 3] * 3,
               [2, 40, 77, 12, 9],
               list(range(1, 40))]
    budgets = [10, 8, 6]

    def serve(slo_itl):
        eng = Engine(spec, params, batch=2, compute_dtype=jnp.float32,
                     cache_dtype=jnp.float32)
        sched = Scheduler(eng, chunk=16, slo_itl_ms=slo_itl)
        sched.warmup()
        reqs = [sched.submit(p, k, _greedy(spec))
                for p, k in zip(prompts, budgets)]
        for _ in range(600):
            if all(r.finished.is_set() for r in reqs):
                break
            sched.step()
        outs = [list(r.tokens(timeout=5.0)) for r in reqs]
        adm = sched.admission.summary() if sched.admission else None
        sched.close()
        return outs, adm

    static_outs, _ = serve(None)
    adaptive_outs, adm = serve(0.0001)  # every step "violates" -> shrink
    assert adm["shrinks"] >= 1, adm  # the width really moved
    assert adaptive_outs == static_outs


def test_zero_compiles_after_warmup_adaptive(tiny):
    """Warmup compiles EVERY ladder rung, so an adaptive run — width
    transitions included — mints zero post-warmup keys, and the same
    run is clean under the --freeze-compiles refusal."""
    spec, params = tiny
    eng = Engine(spec, params, batch=2, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    sched = Scheduler(eng, chunk=16, slo_itl_ms=0.0001)  # always shrink
    sched.warmup()  # warms 16/8/4/2 + decode + arms the sentinel
    before = COMPILES.after_warmup
    prev_freeze = COMPILES.freeze
    COMPILES.freeze = True
    try:
        reqs = [sched.submit(list(range(1, 34)), 6, _greedy(spec)),
                sched.submit([2, 40, 77], 8, _greedy(spec))]
        for _ in range(400):
            if all(r.finished.is_set() for r in reqs):
                break
            sched.step()
        assert all(r.finished.is_set() for r in reqs)
        for r in reqs:
            assert r.finish_reason == "length"  # no frozen refusal
    finally:
        COMPILES.freeze = prev_freeze
        sched.close()
    assert sched.admission.shrinks >= 1  # widths genuinely moved
    assert COMPILES.after_warmup == before


# -- CLI validation (dead-flag rules, parse time) ---------------------------


def test_admission_metrics_render_in_both_tiers():
    """The dllama_admission_* family must ride /metrics on the
    single-supervisor tier AND, replica-labelled, on router tiers whose
    aggregate summary carries no top-level admission block (a tier must
    not lose a metric family to a launch flag — the PR-8 rule)."""
    from distributed_llama_tpu.runtime.trace import render_prometheus

    adm = AdmissionPolicy(32, slo_itl_ms=50.0).summary()
    top = render_prometheus({"admission": adm})
    assert "dllama_admission_chunk_width 32" in top
    assert 'dllama_admission_chunk_changes_total{direction="shrink"}' \
        in top
    routed = render_prometheus({"replicas": [
        {"replica": 0, "state": "ready", "admission": adm},
        {"replica": 1, "state": "ready"}]})
    assert ('dllama_replica_admission_chunk_width{replica="0"} 32'
            in routed)
    assert "dllama_admission_chunk_width" not in routed.replace(
        "dllama_replica_admission", "")


def test_slo_flags_rejected_on_replica_hosts_tier():
    """Pre-started --replica-hosts workers own their configs — the
    parent cannot arm their policies, so SLO flags there are the silent
    dead configuration the parse-time rules exist to refuse."""
    with pytest.raises(SystemExit) as ei:
        dllama.main(["api", "--model", "m", "--tokenizer", "t",
                     "--serve-batch", "2",
                     "--replica-hosts", "h1:9001,h2:9001",
                     "--slo-itl-ms", "80"])
    assert "--replica-hosts" in str(ei.value)


def test_slo_flags_rejected_without_serve_batch():
    with pytest.raises(SystemExit) as ei:
        dllama.main(["api", "--model", "m", "--tokenizer", "t",
                     "--slo-itl-ms", "50"])
    assert "--serve-batch" in str(ei.value)
    with pytest.raises(SystemExit) as ei:
        dllama.main(["api", "--model", "m", "--tokenizer", "t",
                     "--slo-ttft-ms", "500"])
    assert "--serve-batch" in str(ei.value)
    with pytest.raises(SystemExit) as ei:
        dllama.main(["api", "--model", "m", "--tokenizer", "t",
                     "--serve-batch", "2", "--slo-itl-ms", "-5"])
    assert "> 0" in str(ei.value)


def test_auto_sentinels_validate_at_parse_time(tmp_path):
    """'auto' parses (argparse type), garbage does not; auto on the
    process tier is a clear error (no ledger-capable local engine);
    --autotune without an auto sentinel is a dead flag; a bad artifact
    is a startup error naming the problem."""
    import json

    ap = dllama.build_argparser()
    args = ap.parse_args(["api", "--serve-batch", "auto",
                          "--prefix-blocks", "AUTO"])
    assert args.serve_batch == "auto" and args.prefix_blocks == "auto"
    with pytest.raises(SystemExit):
        ap.parse_args(["api", "--serve-batch", "many"])

    with pytest.raises(SystemExit) as ei:
        dllama.main(["api", "--model", "m", "--tokenizer", "t",
                     "--serve-batch", "auto", "--replica-procs", "2"])
    assert "ledger-capable" in str(ei.value)
    with pytest.raises(SystemExit) as ei:
        dllama.main(["api", "--model", "m", "--tokenizer", "t",
                     "--serve-batch", "2", "--autotune", "AUTOTUNE.json"])
    assert "auto" in str(ei.value)
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"kind": "nope"}))
    with pytest.raises(SystemExit) as ei:
        dllama.main(["api", "--model", "m", "--tokenizer", "t",
                     "--serve-batch", "auto", "--autotune", str(bad)])
    assert "kind" in str(ei.value)
