"""Ring attention vs single-device causal attention oracle, on the virtual
8-device CPU mesh (the multi-device SPMD testing pattern the reference lacked,
SURVEY.md §4)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

# compile-heavy SPMD meshes: the slow tier (pytest.ini)
pytestmark = pytest.mark.slow

from distributed_llama_tpu.parallel.mesh import make_mesh
from distributed_llama_tpu.parallel.ring_attention import ring_attention


def _reference_attention(q, k, v, pos0=0):
    """Dense causal softmax attention with GQA, f32."""
    b, t, h, hs = q.shape
    kvh = k.shape[2]
    g = h // kvh
    qf = q.astype(jnp.float32).reshape(b, t, kvh, g, hs)
    scores = jnp.einsum("bqkgd,bskd->bqkgs", qf, k.astype(jnp.float32))
    scores = scores / (hs ** 0.5)
    qpos = pos0 + jnp.arange(t)
    mask = qpos[:, None] >= (pos0 + jnp.arange(t))[None, :]
    scores = jnp.where(mask[None, :, None, None, :], scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bqkgs,bskd->bqkgd", p, v.astype(jnp.float32))
    return out.reshape(b, t, h, hs)


@pytest.mark.parametrize("sp", [2, 4, 8])
@pytest.mark.parametrize("h,kvh", [(4, 4), (8, 2)])
def test_ring_matches_dense(rng, sp, h, kvh):
    mesh = make_mesh(tp=1, sp=sp)
    b, t, hs = 2, 32, 16
    q = jnp.asarray(rng.standard_normal((b, t, h, hs), dtype=np.float32))
    k = jnp.asarray(rng.standard_normal((b, t, kvh, hs), dtype=np.float32))
    v = jnp.asarray(rng.standard_normal((b, t, kvh, hs), dtype=np.float32))

    ref = _reference_attention(q, k, v)
    got = ring_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_ring_with_position_offset(rng):
    """pos0 > 0 (continuing a session) keeps causal masking consistent."""
    mesh = make_mesh(tp=1, sp=4)
    b, t, h, hs = 1, 16, 4, 8
    q = jnp.asarray(rng.standard_normal((b, t, h, hs), dtype=np.float32))
    k = jnp.asarray(rng.standard_normal((b, t, h, hs), dtype=np.float32))
    v = jnp.asarray(rng.standard_normal((b, t, h, hs), dtype=np.float32))
    ref = _reference_attention(q, k, v, pos0=100)
    got = ring_attention(q, k, v, mesh, pos0=100)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_ring_first_token_masked_blocks(rng):
    """Device 0's first rows see only themselves; later ring blocks from
    higher devices must contribute nothing (fully-masked-block handling)."""
    mesh = make_mesh(tp=1, sp=4)
    b, t, h, hs = 1, 8, 2, 4
    q = jnp.asarray(rng.standard_normal((b, t, h, hs), dtype=np.float32))
    k = jnp.asarray(rng.standard_normal((b, t, h, hs), dtype=np.float32))
    v = jnp.asarray(rng.standard_normal((b, t, h, hs), dtype=np.float32))
    got = np.asarray(ring_attention(q, k, v, mesh))
    # token 0 attends only to itself -> output == v[0]
    np.testing.assert_allclose(got[0, 0], np.asarray(v)[0, 0], atol=1e-5)
    assert np.isfinite(got).all()


def test_engine_ring_prefill_matches_plain(rng):
    """Full-model equivalence: an engine on an sp-mesh ring-prefills the
    prompt; logits and subsequent greedy decode must match the meshless
    engine (cache written through the sp path must be consistent)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.params import load_params, random_tensors
    from distributed_llama_tpu.models.spec import ArchType, HiddenAct, ModelSpec
    from distributed_llama_tpu.runtime.engine import Engine

    spec = ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=4, vocab_size=96, seq_len=64,
                     hidden_act=HiddenAct.SILU)
    tensors = random_tensors(spec, seed=9)
    params = load_params(spec, tensors, mode="dense", dtype=jnp.float32)

    prompt = [1, 7, 42, 13, 5, 88, 21]  # 7 tokens -> padded to 8 on sp=4

    plain = Engine(spec, load_params(spec, tensors, mode="dense", dtype=jnp.float32),
                   compute_dtype=jnp.float32, cache_dtype=jnp.float32)
    ref_logits = np.asarray(plain.prefill(prompt))

    mesh = make_mesh(tp=2, sp=4, dp=1)
    ring = Engine(spec, params, mesh, compute_dtype=jnp.float32,
                  cache_dtype=jnp.float32)
    got_logits = np.asarray(ring.prefill(prompt))

    np.testing.assert_allclose(got_logits, ref_logits, atol=1e-4, rtol=1e-4)
    assert ring.pos == plain.pos == len(prompt)

    # greedy decode 4 tokens on both: cache correctness end-to-end
    tok_r = int(np.argmax(got_logits[0]))
    tok_p = int(np.argmax(ref_logits[0]))
    assert tok_r == tok_p
    for _ in range(4):
        lr = np.asarray(ring.step(np.asarray([[tok_r]], np.int32), ring.pos))
        lp = np.asarray(plain.step(np.asarray([[tok_p]], np.int32), plain.pos))
        tok_r, tok_p = int(np.argmax(lr[0])), int(np.argmax(lp[0]))
        assert tok_r == tok_p


def test_sp_cache_is_sequence_sharded(rng):
    """The memory claim: with sp>1 the per-device KV cache shard covers
    seq_len/sp positions (VERDICT r1 #3 — the cache, not just the compute,
    must scale with sp)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.params import load_params, random_tensors
    from distributed_llama_tpu.models.spec import ArchType, HiddenAct, ModelSpec
    from distributed_llama_tpu.parallel.mesh import SP_AXIS
    from distributed_llama_tpu.runtime.engine import Engine

    spec = ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=4, vocab_size=96, seq_len=64,
                     hidden_act=HiddenAct.SILU)
    params = load_params(spec, random_tensors(spec, seed=4), mode="dense",
                         dtype=jnp.float32)
    mesh = make_mesh(tp=2, sp=4, dp=1)
    engine = Engine(spec, params, mesh, compute_dtype=jnp.float32,
                    cache_dtype=jnp.float32)

    k0 = engine.cache.k[0]
    assert k0.sharding.spec[2] == SP_AXIS  # sequence dim sharded over sp
    shard = k0.addressable_shards[0]
    b, kvh, s, hs = k0.shape
    assert shard.data.shape == (b, kvh // 2, s // 4, hs)  # tp=2 heads, sp=4 seq

    # the sharding survives a step (donated update keeps the layout)
    engine.step(np.asarray([[3, 5]], np.int32), 0)
    k0 = engine.cache.k[0]
    assert k0.sharding.spec[2] == SP_AXIS
    assert k0.addressable_shards[0].data.shape == (b, kvh // 2, s // 4, hs)
