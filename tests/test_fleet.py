"""The fleet brain (runtime/fleet.py): load-adaptive autoscaling,
SLO-aware overload shedding, and multi-tenant fairness.

Three tiers of coverage, matching the ISSUE 18 acceptance bars:

  * PURE host-side units (no engine, no sleeps): WFQueue invariants
    (strict priority bands, weighted share, the two-tenant starvation
    bound, the deque duck-type contract ``Scheduler._queue`` relies
    on), TenantLedger token-bucket refill under an injectable clock,
    budget demotion that stays work-conserving, and the ShedLadder's
    monotone rung-by-rung walk with count-based hysteresis + cooldown.
  * FleetController decision units over a FAKE door (tick-driven, zero
    wall-clock dependence): sustained pressure spawns, the scale_flap
    fault proves the anti-flap counters hold, the HBM ledger's
    ``slots_addable`` is a hard ceiling, a dead spawn folds into
    spawn_failures + backoff (never a confused respawn), ``spawn_stall``
    is key-filtered, sustained idle reaps the highest-id idle replica
    down to ``min_replicas``, and the ladder's ``no_spec`` rung lands on
    every local scheduler (and re-lands after a rebuild).
  * Engine-backed e2e (real thread/process replicas): the /readyz +
    ``Router.state`` regression — a draining-for-reap replica must NOT
    flip fleet readiness, and an in-flight scale event reports
    ``scaling_up``/``scaling_down`` — plus a real scale-up → serve →
    scale-down round trip with greedy parity against the single-engine
    oracle. The process-tier regression and the e2e round trip run in
    the CI chaos job (the main matrix deselects them, same split as
    tests/test_bench_outage.py's subprocess smokes).

Everything decision-shaped is count-deterministic: the controller's
``tick()`` is a public synchronous entry point, hysteresis is measured
in ticks, and the ledger takes an injectable clock.
"""

import os
import threading
import time
import types

import pytest

jnp = pytest.importorskip("jax.numpy")

from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.models.params import load_params, random_tensors
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.runtime.faults import FAULTS
from distributed_llama_tpu.runtime.fleet import (DEFAULT_TENANT,
                                                 LADDER_RUNGS, PRIORITIES,
                                                 FleetConfig,
                                                 FleetController,
                                                 ShedLadder, ShedReject,
                                                 TenantLedger, WFQueue,
                                                 parse_tenant_budgets)
from distributed_llama_tpu.runtime.router import ReplicaHandle, Router
from distributed_llama_tpu.sampler import Sampler

SEQ = 64


@pytest.fixture(scope="module")
def tiny():
    spec = ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=128, seq_len=SEQ,
                     hidden_act=HiddenAct.SILU)
    host = random_tensors(spec, seed=3, scale=0.05)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    return spec, params


@pytest.fixture(autouse=True)
def clean_faults():
    FAULTS.clear()
    yield
    FAULTS.clear()


def _factory(tiny, batch=2):
    spec, params = tiny

    def make():
        return Engine(spec, params, batch=batch, compute_dtype=jnp.float32,
                      cache_dtype=jnp.float32)

    return make


def _greedy(spec):
    return Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=1)


def _oracle(spec, params, prompt, max_tokens):
    eng = Engine(spec, params, batch=1, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    return eng.generate(prompt, max_tokens, _greedy(spec)).tokens


def _wait(pred, timeout=30.0, poll=0.01):
    end = time.perf_counter() + timeout
    while time.perf_counter() < end:
        if pred():
            return True
        time.sleep(poll)
    return False


class Req:
    """The slice of scheduler.ServeRequest the WFQueue tags read."""

    def __init__(self, tenant=None, priority="normal", cost=8, tag=None):
        self.tenant = tenant
        self.priority = priority
        self.prompt = list(range(max(cost - 1, 1)))
        self.max_tokens = 1
        self.tag = tag if tag is not None else tenant


# -- parse_tenant_budgets -------------------------------------------------


def test_parse_tenant_budgets_accepts_and_refuses():
    assert parse_tenant_budgets(None) == {}
    assert parse_tenant_budgets("") == {}
    out = parse_tenant_budgets("acme=3:5000, free=1:200 ,solo=2")
    assert out == {"acme": (3.0, 5000.0), "free": (1.0, 200.0),
                   "solo": (2.0, 0.0)}
    for bad in ("noequals", "a=x", "a=1:y", "a=0", "a=-1", "a=1:-5"):
        with pytest.raises(ValueError):
            parse_tenant_budgets(bad)


# -- WFQueue: the deque duck-type + fairness invariants -------------------


def test_wfq_duck_types_the_scheduler_deque_slice():
    q = WFQueue()
    assert len(q) == 0 and not q
    q.append(Req("a"))
    assert len(q) == 1 and q
    assert q.popleft().tenant == "a"
    with pytest.raises(IndexError):
        q.popleft()  # the contract Scheduler._abort_all drains on
    assert q.snapshot_depths() == {p: 0 for p in PRIORITIES}


def test_wfq_strict_priority_across_bands():
    """high drains before normal drains before low, regardless of
    arrival order or tags within a band."""
    q = WFQueue()
    for prio in ("low", "normal", "high", "low", "normal", "high"):
        q.append(Req("t", priority=prio, tag=prio))
    assert q.snapshot_depths() == {"high": 2, "normal": 2, "low": 2}
    got = [q.popleft().tag for _ in range(6)]
    assert got == ["high", "high", "normal", "normal", "low", "low"]
    # an unknown priority string lands in the normal band, not a crash
    q.append(Req("t", priority="nonsense", tag="x"))
    assert q.snapshot_depths()["normal"] == 1
    assert q.popleft().tag == "x"


def test_wfq_weighted_share_within_band():
    """Weight 4 vs weight 1, equal-cost backlogs enqueued alternating:
    the first 10 admissions split 8:2 — the SFQ finish tags realise the
    4:1 share without any scan or sort."""
    ledger = TenantLedger({"big": (4.0, 0.0), "small": (1.0, 0.0)})
    q = WFQueue(ledger)
    for _ in range(10):
        q.append(Req("big", cost=8))
        q.append(Req("small", cost=8))
    first = [q.popleft().tenant for _ in range(10)]
    assert first.count("big") == 8 and first.count("small") == 2


def test_wfq_two_tenant_starvation_bound():
    """A victim arriving BEHIND a 50-deep hog backlog is served within
    one pop: its start tag is the band virtual time, not the end of the
    hog's queue — the bound that keeps a hog's burst out of a victim's
    p99. Same priority band, so this is the WFQ's doing, not the
    priority ladder's."""
    ledger = TenantLedger({"hog": (1.0, 0.0), "victim": (4.0, 0.0)})
    q = WFQueue(ledger)
    for _ in range(50):
        q.append(Req("hog", cost=8))
    # let the hog make progress first so the band virtual time moved
    assert q.popleft().tenant == "hog"
    assert q.popleft().tenant == "hog"
    q.append(Req("victim", cost=8))
    assert q.popleft().tenant == "victim"


def test_wfq_budget_demotes_but_stays_work_conserving():
    """An over-budget tenant is served only when no in-budget tenant
    waits — and IS served then (overage rides idle capacity, it is
    never rejected by the queue)."""
    now = [100.0]
    ledger = TenantLedger({"hog": (1.0, 10.0)}, burst_secs=1.0,
                          clock=lambda: now[0])
    assert ledger.in_budget("hog")          # bucket starts full (10)
    ledger.charge("hog", 20)                # balance -10
    assert not ledger.in_budget("hog")
    q = WFQueue(ledger)
    q.append(Req("hog", cost=2))            # smallest finish tag...
    q.append(Req("payer", cost=8))
    assert q.popleft().tenant == "payer"    # ...but demoted behind budget
    assert q.popleft().tenant == "hog"      # work-conserving fallback
    # refill repays the overage: +2 s at 10 tok/s covers the -10 debt
    now[0] += 2.0
    assert ledger.in_budget("hog")


def test_tenant_ledger_refill_caps_at_burst():
    now = [0.0]
    ledger = TenantLedger({"t": (1.0, 10.0)}, burst_secs=2.0,
                          clock=lambda: now[0])
    ledger.charge("t", 15)                  # 20 - 15 = 5
    assert ledger.summary()["t"]["budget_remaining"] == 5.0
    now[0] += 1000.0                        # refill is capped, not a bank
    assert ledger.summary()["t"]["budget_remaining"] == 20.0
    s = ledger.summary()["t"]
    assert s["admitted"] == 1 and s["tokens_charged"] == 15
    # unlimited tenants report no budget and never demote
    assert ledger.summary().get("t")["weight"] == 1.0
    assert ledger.in_budget("never-seen")
    assert ledger.weight("never-seen") == 1.0


# -- ShedLadder: monotone walk, hysteresis, per-rung semantics ------------


def test_ladder_walks_one_rung_at_a_time_with_cooldown():
    lad = ShedLadder(hi=0.8, lo=0.3, up_after=2, down_after=2, cooldown=2)
    up = [lad.observe(1.0) for _ in range(10)]
    # 2 observations above hi per move, 2 ticks of dead time after each:
    # never skips a rung, tops out at shed and stays
    assert up == [0, 1, 1, 2, 2, 3, 3, 4, 4, 4]
    assert lad.escalations == 4 and lad.name == "shed"
    down = [lad.observe(0.0) for _ in range(10)]
    assert down == [4, 3, 3, 2, 2, 1, 1, 0, 0, 0]
    assert lad.recoveries == 4 and lad.name == "healthy"
    # mid-band pressure resets BOTH hysteresis counters
    lad2 = ShedLadder(hi=0.8, lo=0.3, up_after=2, down_after=2, cooldown=0)
    lad2.observe(1.0)
    lad2.observe(0.5)   # between lo and hi: the streak is broken
    lad2.observe(1.0)
    assert lad2.rung == 0


def test_ladder_rung_semantics_per_request():
    lad = ShedLadder(clamp_tokens=64)
    lad.rung = LADDER_RUNGS.index("no_spec")
    assert lad.spec_degraded
    assert lad.admit(max_tokens=500, prefix_hit=False) == (True, 500, None)
    lad.rung = LADDER_RUNGS.index("clamp")
    assert lad.admit(max_tokens=500, prefix_hit=False) == (True, 64, "clamp")
    assert lad.admit(max_tokens=0, prefix_hit=False) == (True, 64, "clamp")
    assert lad.admit(max_tokens=8, prefix_hit=False) == (True, 8, None)
    lad.rung = LADDER_RUNGS.index("prefix_only")
    allowed, _, reason = lad.admit(max_tokens=8, prefix_hit=False)
    assert (allowed, reason) == (False, "prefix_only")
    assert lad.admit(max_tokens=8, prefix_hit=True) == (True, 8, None)
    lad.rung = LADDER_RUNGS.index("shed")
    allowed, _, reason = lad.admit(max_tokens=8, prefix_hit=True)
    assert (allowed, reason) == (False, "shed")


def test_ladder_retry_after_tracks_drain_rate():
    lad = ShedLadder()
    assert lad.retry_after() == 30.0        # no drain signal: worst case
    lad.observe(0.0, queued=16, drained=6.0)
    assert lad.retry_after() == pytest.approx(16 / 3.0)
    lad.observe(0.0, queued=0, drained=100.0)
    assert lad.retry_after() == 0.5         # floor
    lad.observe(0.0, queued=10_000, drained=0.0)
    assert lad.retry_after() == 30.0        # ceiling


# -- FleetController decision units over a fake door ----------------------


class FakeSched:
    def __init__(self):
        self.spec_degraded = False


class FakeSup:
    def __init__(self):
        self.ready = True
        self._sched = FakeSched()


class FakeHandle:
    has_local_engine = True

    def __init__(self, rid, tier="mixed", load=0):
        self.id = rid
        self.tier = tier
        self.reap = False
        self.draining = False
        self.sup = FakeSup()
        self._load = load
        self.drained = False
        self.reap_at_drain = None

    def load(self):
        return self._load

    def drain(self, timeout=30.0):
        self.reap_at_drain = self.reap  # the mark must precede the drain
        self.drained = True
        return True

    def close(self, timeout=30.0):
        pass

    def note_routed(self, prompt):
        pass


class FakeDoor:
    def __init__(self, n=1, tier="mixed", batch=4):
        self.engine = types.SimpleNamespace(batch=batch)
        self.replicas = [FakeHandle(i, tier) for i in range(n)]
        self.scaling = None
        self._spawn_factory = None
        self._kv_transfer = False
        self._summary = {}
        self.reaped = []

    def summary(self):
        return dict(self._summary)

    def add_replica(self, handle):
        self.replicas.append(handle)

    def reap_replica(self, rid, timeout=30.0):
        self.reaped.append(rid)
        self.replicas = [h for h in self.replicas if h.id != rid]


def _settle(fc, timeout=30.0):
    for t in list(fc._scaling_threads):
        t.join(timeout=timeout)
        assert not t.is_alive()


def _cfg(**kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 3)
    kw.setdefault("up_after", 2)
    kw.setdefault("down_after", 2)
    kw.setdefault("cooldown_ticks", 0)
    kw.setdefault("ewma_alpha", 1.0)  # ewma == raw pressure: exact ticks
    return FleetConfig(**kw)


def test_controller_scales_up_on_sustained_pressure():
    door = FakeDoor(n=1)
    door.replicas[0]._load = 8          # pressure 8 / (1*4) = 2.0
    door._spawn_factory = lambda rid, tier: FakeHandle(rid, tier)
    fc = FleetController(door, config=_cfg())
    fc.tick()                           # above = 1: not yet
    assert len(door.replicas) == 1
    fc.tick()                           # above = 2 = up_after: spawn
    _settle(fc)
    assert [h.id for h in door.replicas] == [0, 1]
    assert fc.stats.scale_ups == 1
    assert fc.stats.target_replicas == 2
    assert door.scaling is None         # cleared when the spawn lands
    assert fc.summary()["actual_replicas"] == 2
    # at max_replicas the walk refuses further spawns
    door.replicas.append(FakeHandle(2))
    fc.tick()
    fc.tick()
    _settle(fc)
    assert len(door.replicas) == 3 and fc.stats.scale_ups == 1


def test_concurrent_spawns_mint_distinct_ids_and_respect_ceiling():
    """A spawn can take minutes. A second decision inside that window
    must count the in-flight spawn toward max_replicas and mint a
    DISTINCT id — never a duplicate add_replica, never an overshoot
    (the double-mint race the bench's first run exposed)."""
    door = FakeDoor(n=1)
    door.replicas[0]._load = 8
    gate = threading.Event()
    minted = []

    def slow_factory(rid, tier):
        minted.append(rid)
        gate.wait(timeout=30.0)
        return FakeHandle(rid, tier)

    door._spawn_factory = slow_factory
    fc = FleetController(door, config=_cfg(up_after=1, max_replicas=3))
    fc.tick()                           # spawn r1 (parked on the gate)
    assert _wait(lambda: minted == [1])
    fc.tick()                           # r1 still pending: mints r2
    assert _wait(lambda: minted == [1, 2])
    fc.tick()                           # 1 live + 2 pending = max: refused
    gate.set()
    _settle(fc)
    assert minted == [1, 2]
    assert sorted(h.id for h in door.replicas) == [0, 1, 2]
    assert fc.stats.scale_ups == 2 and fc.stats.spawn_failures == 0


def test_controller_scale_flap_fault_proves_antiflap():
    """scale_flap replaces the measured pressure with a 1.0/0.0 square
    wave — count-based hysteresis must ride it out with ZERO decisions
    in either direction."""
    door = FakeDoor(n=2)
    attempts = []
    door._spawn_factory = lambda rid, tier: attempts.append(rid)
    fc = FleetController(door, config=_cfg())
    FAULTS.arm("scale_flap", times=8)
    for _ in range(8):
        fc.tick()
    _settle(fc)
    assert FAULTS.fired("scale_flap") == 8
    assert attempts == [] and door.reaped == []
    assert fc.stats.scale_ups == 0 and fc.stats.scale_downs == 0
    assert fc.stats.ticks == 8


def test_controller_hbm_ceiling_blocks_spawn():
    door = FakeDoor(n=1)
    door.replicas[0]._load = 8
    door._spawn_factory = lambda rid, tier: FakeHandle(rid, tier)
    door._summary = {"replicas": [{"hbm": {"slots_addable": 0}}]}
    fc = FleetController(door, config=_cfg())
    for _ in range(4):
        fc.tick()
    _settle(fc)
    assert len(door.replicas) == 1
    assert fc.stats.scale_ups == 0
    assert fc.stats.scale_blocked_hbm >= 1
    # headroom appears (an eviction, a reap elsewhere): the next
    # sustained window spawns
    door._summary = {"replicas": [{"hbm": {"slots_addable": 4}}]}
    fc.tick()
    fc.tick()
    _settle(fc)
    assert len(door.replicas) == 2 and fc.stats.scale_ups == 1


def test_controller_spawn_failure_folds_into_backoff():
    """A spawn that dies (the SIGKILL-mid-scale-up shape) counts one
    spawn_failure and backs the walk off for spawn_backoff_ticks —
    never a half-entered handle, never a tight respawn loop."""
    door = FakeDoor(n=1)
    door.replicas[0]._load = 8
    attempts = []

    def dying_factory(rid, tier):
        attempts.append(rid)
        raise RuntimeError("injected spawn death")

    door._spawn_factory = dying_factory
    fc = FleetController(door, config=_cfg(up_after=1,
                                           spawn_backoff_ticks=3))
    fc.tick()
    _settle(fc)
    assert attempts == [1]
    assert fc.stats.spawn_failures == 1
    assert len(door.replicas) == 1 and door.scaling is None
    fc.tick()   # backoff 3 -> 2: no new attempt
    fc.tick()   # 2 -> 1: still backing off
    _settle(fc)
    assert attempts == [1]
    door._spawn_factory = lambda rid, tier: FakeHandle(rid, tier)
    fc.tick()   # 1 -> 0: backoff expired, the walk tries again
    _settle(fc)
    assert len(door.replicas) == 2 and fc.stats.scale_ups == 1


def test_spawn_stall_fault_is_key_filtered():
    """An armed spawn_stall carrying key=rK neither stalls NOR counts
    for any other replica's spawn — one scale-up can be stalled
    deterministically while siblings spawn clean."""
    door = FakeDoor(n=1)
    door.replicas[0]._load = 8
    door._spawn_factory = lambda rid, tier: FakeHandle(rid, tier)
    fc = FleetController(door, config=_cfg(up_after=1))
    FAULTS.arm("spawn_stall", key="r99", ms=60_000)  # not our replica
    fc.tick()
    _settle(fc)
    assert len(door.replicas) == 2
    assert FAULTS.fired("spawn_stall") == 0          # not even a hit
    # now stall THE replica the next scale-up mints (rid 2), briefly
    FAULTS.clear()
    FAULTS.arm("spawn_stall", key="r2", ms=100)
    door.replicas[0]._load = 12
    door.replicas[1]._load = 12
    fc.tick()
    _settle(fc)
    assert FAULTS.fired("spawn_stall") == 1
    assert len(door.replicas) == 3                   # stalled, not dead


def test_controller_scales_down_idle_and_respects_floor():
    door = FakeDoor(n=3)
    door._spawn_factory = lambda rid, tier: FakeHandle(rid, tier)
    fc = FleetController(door, config=_cfg(min_replicas=2))
    fc.tick()                           # idle = 1 (pressure 0 < 0.15)
    fc.tick()                           # idle = 2 = down_after: reap
    _settle(fc)
    assert door.reaped == [2]           # highest-id idle victim
    assert fc.stats.scale_downs == 1 and door.scaling is None
    # the reap mark preceded the drain (the /readyz satellite's ordering)
    fc.tick()
    fc.tick()
    _settle(fc)
    assert door.reaped == [2]           # min_replicas=2 is the floor
    assert len(door.replicas) == 2


def test_reap_mark_precedes_drain():
    door = FakeDoor(n=2)
    door._spawn_factory = lambda rid, tier: FakeHandle(rid, tier)
    fc = FleetController(door, config=_cfg())
    victim = door.replicas[1]
    fc.tick()
    fc.tick()
    _settle(fc)
    assert victim.drained and victim.reap_at_drain is True


def test_controller_never_reaps_last_replica():
    door = FakeDoor(n=1)
    door._spawn_factory = lambda rid, tier: FakeHandle(rid, tier)
    fc = FleetController(door, config=_cfg(min_replicas=1))
    for _ in range(6):
        fc.tick()
    _settle(fc)
    assert door.reaped == [] and len(door.replicas) == 1


def test_controller_applies_and_recovers_degrade():
    """Rung >= no_spec lands on every local scheduler, re-lands after a
    rebuild (fresh scheduler object), and recovery clears it."""
    door = FakeDoor(n=1)
    h = door.replicas[0]
    h._load = 8
    lad = ShedLadder(hi=0.8, lo=0.3, up_after=1, down_after=1, cooldown=0)
    fc = FleetController(door, ladder=lad)
    fc.tick()
    assert lad.rung == 1 and h.sup._sched.spec_degraded
    h.sup._sched = FakeSched()          # supervisor rebuild mid-degrade
    assert not h.sup._sched.spec_degraded
    fc.tick()                           # re-applied within one tick
    assert h.sup._sched.spec_degraded   # (and escalated again: rung 2)
    assert lad.rung == 2
    h._load = 0
    fc.tick()                           # rung 2 -> 1: still degraded
    fc.tick()                           # rung 1 -> 0: recovered
    assert lad.rung == 0 and not h.sup._sched.spec_degraded
    assert fc.stats.rung == 0


def test_controller_admit_accounts_clamps_and_sheds():
    lad = ShedLadder(clamp_tokens=64)
    ledger = TenantLedger({"acme": (2.0, 0.0)})
    fc = FleetController(FakeDoor(n=1), ladder=lad, ledger=ledger)
    # healthy: pass-through
    assert fc.admit(tenant="acme", n_prompt=4, max_tokens=500) == 500
    lad.rung = LADDER_RUNGS.index("clamp")
    assert fc.admit(tenant="acme", n_prompt=4, max_tokens=500) == 64
    assert fc.stats.clamped == 1
    lad.rung = LADDER_RUNGS.index("prefix_only")
    assert fc.admit(tenant="acme", n_prompt=4, max_tokens=8,
                    prefix_hit=True) == 8
    with pytest.raises(ShedReject) as e:
        fc.admit(tenant="acme", n_prompt=4, max_tokens=8, prefix_hit=False)
    assert e.value.reason == "prefix_only"
    lad.rung = LADDER_RUNGS.index("shed")
    with pytest.raises(ShedReject) as e:
        fc.admit(tenant=None, n_prompt=4, max_tokens=8)
    assert e.value.reason == "shed"
    assert 0.5 <= e.value.retry_after <= 30.0
    assert fc.stats.sheds == 2
    assert fc.stats.sheds_by_reason == {"prefix_only": 1, "shed": 1}
    tenants = fc.summary()["tenants"]
    assert tenants["acme"]["shed"] == 1
    assert tenants[DEFAULT_TENANT]["shed"] == 1
    # no ladder (no SLO flags): admit never touches the request
    fc2 = FleetController(FakeDoor(n=1))
    assert fc2.admit(tenant="x", n_prompt=1, max_tokens=10 ** 6) == 10 ** 6


def test_controller_summary_shape():
    door = FakeDoor(n=2)
    door._spawn_factory = lambda rid, tier: FakeHandle(rid, tier)
    fc = FleetController(door, config=_cfg(),
                         ladder=ShedLadder(),
                         ledger=TenantLedger({"a": (1.0, 0.0)}))
    s = fc.summary()
    assert s["actual_replicas"] == 2 and s["target_replicas"] == 2
    assert s["min_replicas"] == 1 and s["max_replicas"] == 3
    assert s["autoscaling"] is True
    assert s["ladder"]["name"] == "healthy"
    assert "a" in s["tenants"]
    # a reap-marked replica is not actual capacity
    door.replicas[1].reap = True
    assert fc.summary()["actual_replicas"] == 1


def test_prefill_and_serve_tiers_observed_independently():
    door = FakeDoor(n=2)
    door.replicas[1].tier = "prefill"
    door.replicas[0]._load = 8          # serve pressure 2.0
    door.replicas[1]._load = 0          # prefill pressure 0.0
    fc = FleetController(door)
    obs = fc.tick()["obs"]
    assert obs["serve"][0] == pytest.approx(2.0)
    assert obs["prefill"][0] == pytest.approx(0.0)
    # a reap-marked replica is excluded from the signal entirely
    door.replicas[1].reap = True
    assert "prefill" not in fc.tick()["obs"]


# -- engine-backed: /readyz + state regression (thread tier) --------------


def test_reap_mark_does_not_flip_readiness_thread_tier(tiny):
    """Satellite 2: a replica draining FOR REAP is a capacity decision —
    /readyz stays ready, Router.state stays "ready" (or reports the
    in-flight scale direction), and requests route around the victim."""
    spec, params = tiny
    router = Router(_factory(tiny), replicas=2, chunk=8,
                    stall_timeout=60.0, backoff_base=0.01)
    try:
        assert _wait(lambda: router.ready)
        assert router.state == "ready"
        router.replicas[1].reap = True
        assert router.ready                     # sibling still routable
        assert router.state == "ready"          # NOT "draining"
        router.scaling = "scaling_down"
        assert router.state == "scaling_down"   # in-flight scale event
        router.scaling = None
        # the reaped replica never takes traffic
        p = [1, 2, 3]
        got = list(router.submit(p, 3, _greedy(spec)).tokens(timeout=60.0))
        assert got == _oracle(spec, params, p, 3)
        assert router.replicas[1].load() == 0
        # every replica reap-marked: the tier is draining, and an
        # in-flight scale event still wins the report
        router.replicas[0].reap = True
        assert not router.ready
        assert router.state == "draining"
        router.scaling = "scaling_up"
        assert router.state == "scaling_up"
    finally:
        router.scaling = None
        for h in router.replicas:
            h.reap = False
        router.close()


# -- engine-backed e2e: scale-up -> serve -> scale-down (chaos job) -------


def test_fleet_scale_roundtrip_thread_tier(tiny):
    """A real scale-up (fresh supervised replica over shared weights),
    greedy parity through the grown fleet, then a scale-down that reaps
    the newest replica — readiness never flickers."""
    spec, params = tiny
    factory = _factory(tiny)
    sup_kwargs = dict(chunk=8, stall_timeout=60.0)
    router = Router(factory, replicas=2, chunk=8, stall_timeout=60.0,
                    backoff_base=0.01)
    router._spawn_factory = lambda rid, tier: ReplicaHandle(
        rid, factory, sup_kwargs, tier=tier)
    fc = FleetController(router, config=FleetConfig(
        min_replicas=2, max_replicas=3, up_pressure=-1.0,
        down_pressure=-2.0, up_after=1, down_after=1,
        cooldown_ticks=0, ewma_alpha=1.0))
    try:
        assert _wait(lambda: router.ready)
        fc.tick()                       # pressure 0 > -1: scale up
        _settle(fc, timeout=120.0)
        assert [h.id for h in router.replicas] == [0, 1, 2]
        assert fc.stats.scale_ups == 1
        assert router.ready and router.state == "ready"
        p = [2, 4, 6]
        got = list(router.submit(p, 3, _greedy(spec)).tokens(timeout=60.0))
        assert got == _oracle(spec, params, p, 3)
        before = router.summary()["requests_finished"]
        # flip the thresholds: idle now reads as scale-down pressure
        fc.config.up_pressure = 10.0
        fc.config.down_pressure = 10.0
        fc.tick()
        _settle(fc, timeout=60.0)
        assert [h.id for h in router.replicas] == [0, 1]
        assert fc.stats.scale_downs == 1
        assert router.ready and router.state == "ready"
        # counter totals survive the reap (the _reap_carry fold)
        assert router.summary()["requests_finished"] >= before
        got = list(router.submit(p, 3, _greedy(spec)).tokens(timeout=60.0))
        assert got == _oracle(spec, params, p, 3)
    finally:
        fc.close()
        router.close()


# -- process tier: reap/state regression (chaos job) ----------------------


def test_reap_mark_does_not_flip_readiness_process_tier(tmp_path):
    """The same satellite-2 regression across the REAL fault boundary:
    two spawned worker processes, one reap-marked — the tier stays
    ready and the state report never calls a controller decision a
    health problem."""
    from distributed_llama_tpu.runtime.replica_worker import WorkerProc
    from distributed_llama_tpu.runtime.router import RemoteReplicaHandle

    cfg = {"test_spec": dict(dim=64, hidden_dim=128, n_layers=2, n_heads=4,
                             n_kv_heads=2, vocab_size=128, seq_len=SEQ),
           "seed": 3, "scale": 0.05, "compute_dtype": "f32", "batch": 2,
           "serve": {"stall_timeout": 60.0}}
    wenv = {"JAX_PLATFORMS": "cpu",
            "JAX_COMPILATION_CACHE_DIR": os.path.join(
                os.path.expanduser("~"), ".cache", "dllama_tpu_xla"),
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "1.0"}

    def mk(i):
        proc = WorkerProc(i, dict(cfg, fault_key=f"r{i}"),
                          workdir=str(tmp_path), env=wenv)
        return RemoteReplicaHandle(i, proc=proc, poll_interval=0.1,
                                   spawn_timeout=120.0,
                                   respawn_timeout=120.0)

    handles = [None, None]

    def build(i):
        handles[i] = mk(i)

    threads = [threading.Thread(target=build, args=(i,)) for i in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(h is not None for h in handles), "worker spawn failed"
    router = Router(None, handle_factories=[lambda: handles[0],
                                            lambda: handles[1]])
    try:
        assert _wait(lambda: router.ready, timeout=120.0)
        router.replicas[1].reap = True
        assert router.ready and router.state == "ready"
        router.scaling = "scaling_down"
        assert router.state == "scaling_down"
        router.scaling = None
        # traffic routes around the reap-marked worker
        sam = Sampler(128, temperature=0.0, topp=0.9, seed=1)
        got = list(router.submit([1, 2, 3], 3, sam).tokens(timeout=60.0))
        assert len(got) == 3
    finally:
        router.close()
