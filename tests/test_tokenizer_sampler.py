"""Tokenizer BPE + sampler behavior tests.

The reference ships no tokenizer/sampler tests (SURVEY.md §4 gap); these
pin the behaviors ported from src/tokenizer.cpp.
"""

import numpy as np

from distributed_llama_tpu.io.tokenizer_file import TokenizerData
from distributed_llama_tpu.sampler import Sampler
from distributed_llama_tpu.tokenizer import Tokenizer
from distributed_llama_tpu.utils.rng import xorshift_f32


def make_tokenizer():
    # minimal llama2.c-style vocab: 3 specials, 256 byte tokens, then words
    vocab = [b"<unk>", b"<s>", b"</s>"]
    vocab += [bytes([i]) if False else f"<0x{i:02X}>".encode() for i in range(256)]
    words = [b" ", b"a", b"b", b"c", b"ab", b"bc", b"abc", b" abc", b"he", b"llo", b"hello", b" hello"]
    scores = [0.0] * len(vocab) + [-float(i + 1) for i in range(len(words))]
    # give longer merges higher scores so greedy merging prefers them
    vocab += words
    scores[vocab.index(b"ab")] = -0.5
    scores[vocab.index(b"abc")] = -0.2
    scores[vocab.index(b" abc")] = -0.1
    scores[vocab.index(b"hello")] = -0.3
    scores[vocab.index(b" hello")] = -0.25
    scores[vocab.index(b"he")] = -0.6
    scores[vocab.index(b"llo")] = -0.55
    return Tokenizer(TokenizerData(vocab=vocab, scores=scores, bos_id=1, eos_id=2))


def test_encode_merges_to_longest():
    tok = make_tokenizer()
    ids = tok.encode("abc", add_bos=True)
    assert ids[0] == tok.bos_id
    assert tok.vocab[ids[-1]] == b" abc"  # dummy space prefix merged in
    assert len(ids) == 2


def test_encode_byte_fallback():
    tok = make_tokenizer()
    ids = tok.encode("z", add_bos=False)  # 'z' not in vocab -> byte token +3
    assert ids[-1] == ord("z") + 3  # ref: src/tokenizer.cpp:184-189


def test_decode_strips_bos_space_and_bytes():
    tok = make_tokenizer()
    ids = tok.encode("hello", add_bos=True)
    assert tok.decode(ids) == "hello"
    # raw byte piece expansion (ref: src/tokenizer.cpp:93-98)
    assert tok.decode_piece(-1, ord("z") + 3) == b"z"


def test_encode_eos():
    tok = make_tokenizer()
    ids = tok.encode("a", add_bos=True, add_eos=True)
    assert ids[-1] == tok.eos_id


def test_sampler_greedy():
    s = Sampler(vocab_size=10, temperature=0.0, topp=0.9, seed=1)
    logits = np.zeros(10, np.float32)
    logits[7] = 3.0
    assert s.sample(logits) == 7


def test_sampler_mult_matches_manual_cdf():
    # ref: src/tokenizer.cpp:244-255 — first index where coin < cdf
    seed = 42
    s = Sampler(vocab_size=4, temperature=1.0, topp=0.0, seed=seed)
    logits = np.log(np.array([0.1, 0.2, 0.3, 0.4], np.float32))
    _, coin = xorshift_f32(seed)
    probs = np.exp(logits - logits.max())
    probs /= probs.sum()
    cdf = np.cumsum(probs)
    expect = int(np.searchsorted(cdf, coin, side="right"))
    assert s.sample(logits.copy()) == expect


def test_sampler_topp_truncates():
    # with topp=0.5 and a dominant token, only the top token can be sampled
    s = Sampler(vocab_size=5, temperature=1.0, topp=0.5, seed=7)
    logits = np.array([10.0, 0.0, 0.0, 0.0, 0.0], np.float32)
    for _ in range(20):
        assert s.sample(logits.copy()) == 0


def test_sampler_seed_reproducible():
    logits = np.random.default_rng(0).standard_normal(100).astype(np.float32)
    a = Sampler(100, 0.8, 0.9, seed=123)
    b = Sampler(100, 0.8, 0.9, seed=123)
    seq_a = [a.sample(logits.copy()) for _ in range(10)]
    seq_b = [b.sample(logits.copy()) for _ in range(10)]
    assert seq_a == seq_b


def test_stop_token_ids_include_chat_markers():
    from distributed_llama_tpu.io.tokenizer_file import TokenizerData
    from distributed_llama_tpu.tokenizer import Tokenizer

    vocab = [b"<unk>", b"<s>", b"</s>", b"a", b"<|eot_id|>", b"<|eom_id|>"]
    t = Tokenizer(TokenizerData(vocab=vocab, scores=[0.0] * 6, bos_id=1, eos_id=2))
    # eos plus every end-of-turn marker present in the vocab (llama-3 instruct
    # ends turns with <|eot_id|> while eos_id is the base-model eos)
    assert t.stop_token_ids() == {2, 4, 5}

    t2 = Tokenizer(TokenizerData(vocab=vocab[:4], scores=[0.0] * 4, bos_id=1, eos_id=2))
    assert t2.stop_token_ids() == {2}


def test_sample_batch_matches_per_row_stream():
    """Sampler.sample_batch must be token-for-token identical to calling
    sample() per selected row in row order (greedy, multinomial, top-p,
    near-empty-nucleus), with masked rows consuming no coins — the dp
    batch decode path substitutes it for the per-row Python loop."""
    rng = np.random.default_rng(7)
    for temp, topp in ((0.0, 0.9), (0.8, 1.0), (0.8, 0.9), (1.3, 0.5),
                       (0.7, 0.0001)):
        for _ in range(5):
            scale = float(rng.uniform(0.3, 4.0))
            logits = (rng.standard_normal((6, 200)) * scale).astype(np.float32)
            mask = rng.random(6) < 0.7
            if not mask.any():
                mask[0] = True
            a = Sampler(200, temp, topp, seed=99, backend="python")
            b = Sampler(200, temp, topp, seed=99, backend="python")
            want = np.full(6, -1, np.int64)
            for i in np.nonzero(mask)[0]:
                want[i] = a.sample(logits[i])
            got = b.sample_batch(logits, mask)
            np.testing.assert_array_equal(got, want, err_msg=f"{temp},{topp}")
            assert a.rng_state == b.rng_state  # same stream position after
