"""Flight recorder (runtime/trace.py): ring semantics, span recording
through the real scheduler, the /metrics Prometheus plane across all
three serving tiers, /admin/trace JSONL export, cross-process span
rebase, and the two acceptance bars the ISSUE pins:

  * tracing-enabled overhead <= 2% of a decode step (measured against
    the REAL slot_decode_step on the tiny model — the tracer's per-step
    cost is microseconds against a multi-millisecond step);
  * the disabled path is an allocation-free no-op (the call-site
    ``if TRACER.enabled:`` guard runs before any kwargs dict exists).

The HTTP tier tests drive the real ThreadingHTTPServer handlers, same
discipline as tests/test_apps.py; a tiny Prometheus text parser
validates exposition-format invariants (one HELP/TYPE per metric,
sample lines parse, labels quoted) instead of eyeballing strings.
"""

import http.client
import json
import re
import threading
import time

import pytest

jnp = pytest.importorskip("jax.numpy")

from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.models.params import load_params, random_tensors
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.runtime.scheduler import Scheduler
from distributed_llama_tpu.runtime.trace import (TRACER, Tracer, _sampled,
                                                 render_prometheus)
from distributed_llama_tpu.sampler import Sampler

SEQ = 64


@pytest.fixture(scope="module")
def tiny():
    spec = ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=128, seq_len=SEQ,
                     hidden_act=HiddenAct.SILU)
    host = random_tensors(spec, seed=3, scale=0.05)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    return spec, params


@pytest.fixture(autouse=True)
def clean_tracer():
    TRACER.reset()
    yield
    TRACER.reset()


def _greedy(spec):
    return Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=1)


def _engine(tiny, batch=2):
    spec, params = tiny
    return Engine(spec, params, batch=batch, compute_dtype=jnp.float32,
                  cache_dtype=jnp.float32)


# -- ring + core semantics --------------------------------------------------


def test_ring_is_bounded_and_keeps_newest():
    TRACER.configure(capacity=32)
    for i in range(100):
        TRACER.event("enqueue", i + 1, seq=i)
    evs = TRACER.recent(0)
    assert len(evs) == 32
    assert [e["seq"] for e in evs] == list(range(68, 100))
    assert TRACER.recent(5) == evs[-5:]


def test_by_id_selects_one_span():
    TRACER.configure(capacity=128)
    a, b = TRACER.new_id(), TRACER.new_id()
    TRACER.event("enqueue", a)
    TRACER.event("enqueue", b)
    TRACER.event("finish", a, reason="stop")
    span = TRACER.by_id(a)
    assert [e["kind"] for e in span] == ["enqueue", "finish"]
    assert all(e["tid"] == a for e in span)


def test_disabled_records_nothing_and_is_allocation_free():
    """The off path: no ring growth, and the call-site guard pattern
    (`if TRACER.enabled:`) allocates nothing — conftest disables
    automatic GC, so getallocatedblocks deltas are deterministic."""
    import sys

    assert not TRACER.enabled
    TRACER.event("enqueue", 1, n_prompt=5)   # direct call: still a no-op
    TRACER.step(decode_rows=1, prefill_rows=0, chunk=0, queue_depth=0,
                wall_ms=1.0)
    assert TRACER.recent(0) == []
    assert TRACER.step_timeline() == {}

    def guarded_loop(n):
        for _ in range(n):
            if TRACER.enabled:  # the pattern every hot call site uses
                TRACER.event("decode", 1, n_out=1)

    guarded_loop(10)  # warm the code object/locals
    before = sys.getallocatedblocks()
    guarded_loop(10_000)
    grew = sys.getallocatedblocks() - before
    assert grew < 50, f"disabled guard allocated {grew} blocks"


def test_sampling_is_deterministic_per_id():
    assert _sampled(123, 1.0) and not _sampled(123, 0.0)
    picks = {tid: _sampled(tid, 0.3) for tid in range(1, 2000)}
    assert picks == {tid: _sampled(tid, 0.3) for tid in range(1, 2000)}
    frac = sum(picks.values()) / len(picks)
    assert 0.2 < frac < 0.4  # hash spreads sequential ids


def test_sink_rotation_and_jsonl(tmp_path):
    sink_dir = str(tmp_path / "traces")
    t = Tracer()
    t.configure(capacity=64, sink_dir=sink_dir, sink_max_bytes=2000,
                sink_max_files=3)
    for i in range(200):
        t.event("enqueue", i + 1, n_prompt=4)
    files = sorted((tmp_path / "traces").glob("trace-*.jsonl"))
    assert 1 < len(files) <= 3  # rotated AND bounded
    for f in files:
        for line in f.read_text().splitlines():
            rec = json.loads(line)
            assert rec["kind"] == "enqueue" and "ts_wall" in rec
    t.reset()


def test_sink_sampling_drops_unsampled_spans(tmp_path):
    sink_dir = str(tmp_path / "traces")
    t = Tracer()
    t.configure(capacity=4096, sink_dir=sink_dir, sample=0.0)
    t.event("enqueue", 7, n_prompt=4)      # span event: sampled out
    t.event("fault", 0, site="step_raise")  # tid 0 infra: always kept
    t.reset()  # closes the sink, flushing
    lines = []
    for f in (tmp_path / "traces").glob("trace-*.jsonl"):
        lines += f.read_text().splitlines()
    kinds = [json.loads(ln)["kind"] for ln in lines]
    assert kinds == ["fault"]
    assert len(t.by_id(7)) == 0  # reset cleared the ring too


def test_span_reads_survive_concurrent_appends():
    """by_id/export_span run on pump/HTTP threads while step threads
    append lock-free: they must snapshot the deque first — iterating it
    live raises "deque mutated during iteration" (review-found: the
    worker's _ship_trace would then drop the terminal frame and fabricate
    a replica_lost failover for a healthy worker)."""
    TRACER.configure(capacity=4096)
    stop = threading.Event()
    errs = []

    def writer():
        i = 0
        while not stop.is_set():
            TRACER.event("decode", (i % 7) + 1, n_out=i)
            i += 1

    def reader():
        try:
            for _ in range(3000):
                TRACER.by_id(3)
                TRACER.export_span(4)
        except RuntimeError as e:  # pragma: no cover — the regression
            errs.append(e)

    w = threading.Thread(target=writer, daemon=True)
    w.start()
    try:
        reader()
    finally:
        stop.set()
        w.join(timeout=10)
    assert not errs, errs


def test_export_span_ingest_rebases_cross_process():
    """Worker -> parent span shipping: the wall-stamped export lands on
    the ingesting tracer's monotonic timeline in event order."""
    worker = Tracer()
    worker.configure(capacity=64)
    tid = 42
    worker.event("enqueue", tid, n_prompt=3)
    worker.event("finish", tid, reason="stop")
    shipped = worker.export_span(tid)
    assert all("ts_wall" in e for e in shipped)

    TRACER.configure(capacity=64)
    TRACER.event("route", tid, replica=0, reason="fallback")
    TRACER.ingest(shipped, origin="worker@x:1")
    span = TRACER.by_id(tid)
    assert [e["kind"] for e in span] == ["route", "enqueue", "finish"]
    assert span[1]["origin"] == "worker@x:1"
    # rebased timestamps are on THIS tracer's clock: within a second of
    # now, and ordered
    now = time.perf_counter()
    assert all(abs(e["ts"] - now) < 5.0 for e in span)
    assert span[1]["ts"] <= span[2]["ts"]
    worker.reset()


# -- span + step timeline through the real scheduler ------------------------


def test_scheduler_records_span_and_step_timeline(tiny):
    spec, _ = tiny
    TRACER.configure(capacity=4096, decode_every=2)
    eng = _engine(tiny)
    sched = Scheduler(eng, chunk=8)
    req = sched.submit([1, 9, 23, 54, 7, 11, 40, 3, 15], 6, _greedy(spec))
    while not req.finished.is_set():
        sched.step()
    sched.close()

    assert req.trace_id > 0
    span = TRACER.by_id(req.trace_id)
    kinds = [e["kind"] for e in span]
    # lifecycle order: enqueue -> admit -> prefill chunks -> first token
    # -> decode progress -> finish
    assert kinds[0] == "enqueue"
    assert "admit" in kinds and "prefill" in kinds
    assert kinds.index("admit") < kinds.index("prefill")
    assert "first_token" in kinds
    assert kinds[-1] == "finish"
    fin = span[-1]
    assert fin["reason"] == "length" and fin["n_out"] == 6
    # 9-token prompt at chunk 8 = exactly 2 prefill events
    assert kinds.count("prefill") == 2
    assert kinds.count("decode") >= 1  # cadence 2 over 6 tokens
    # timestamps are monotonic within the span (one clock domain)
    assert all(a["ts"] <= b["ts"] for a, b in zip(span, span[1:]))

    tl = TRACER.step_timeline()
    assert tl, "no step records"
    assert any(k[1] > 0 for k in tl)  # a prefill composition
    assert any(k[0] > 0 and k[1] == 0 for k in tl)  # a pure-decode one
    assert all(v["p50_ms"] >= 0 and v["n"] > 0 for v in tl.values())


def test_prefix_seed_event_records_hit_length(tiny):
    """The span's `seed` event carries the prefix-cache hit length: 0 on
    the cold serve, the whole-block match on the warm repeat (the same
    len-1-capped rule PrefixCache.lookup_pin applies)."""
    from distributed_llama_tpu.runtime.prefix_cache import PrefixCache

    spec, _ = tiny
    TRACER.configure(capacity=2048)
    eng = _engine(tiny)
    pc = PrefixCache(eng, num_blocks=16, block_len=4)
    sched = Scheduler(eng, chunk=8, prefix_cache=pc)
    sched.warmup()
    p = [1, 9, 23, 54, 7, 11, 40, 3, 15]  # two whole 4-token blocks
    outs = []
    reqs = []
    for _ in range(2):
        req = sched.submit(p, 3, _greedy(spec))
        while not req.finished.is_set():
            sched.step()
        outs.append(list(req.tokens(timeout=5.0)))
        reqs.append(req)
    sched.close()
    assert outs[0] == outs[1]  # seeded == cold (the parity guarantee)
    seeds = [next(e for e in TRACER.by_id(r.trace_id)
                  if e["kind"] == "seed") for r in reqs]
    assert seeds[0]["hit"] == 0      # cold
    assert seeds[1]["hit"] == 8      # two published whole blocks
    assert all(s["n_prompt"] == len(p) for s in seeds)


def test_error_frames_record_error_events(tiny):
    spec, _ = tiny
    TRACER.configure(capacity=1024)
    eng = _engine(tiny)
    sched = Scheduler(eng, chunk=8)
    req = sched.submit([1, 2, 3], 4, _greedy(spec))
    sched.close()  # fails queued work with structured shutdown frames
    span = TRACER.by_id(req.trace_id)
    err = [e for e in span if e["kind"] == "error"]
    assert err and err[-1]["code"] == "shutdown"
    assert err[-1]["retryable"] is False


def test_fired_fault_sites_land_on_timeline(tiny):
    from distributed_llama_tpu.runtime.faults import FAULTS, FaultError

    spec, _ = tiny
    TRACER.configure(capacity=1024)
    eng = _engine(tiny)
    sched = Scheduler(eng, chunk=8)
    FAULTS.arm("step_raise", after=0, times=1)
    try:
        sched.submit([1, 2, 3], 2, _greedy(spec))
        with pytest.raises(FaultError):
            sched.step()
    finally:
        FAULTS.clear()
        sched.close()
    fired = [e for e in TRACER.recent(0) if e["kind"] == "fault"]
    assert fired and fired[0]["site"] == "step_raise"


# -- the <= 2% overhead acceptance bar --------------------------------------


def test_tracing_overhead_at_most_two_percent_of_decode_step(tiny):
    """ISSUE 9 acceptance: enabled tracing costs <= 2% of the decode-step
    microbench. Measured composition: per-iteration cost = one step()
    record + the per-token span events a worst-case step emits (every
    row at the decode_every cadence), timed tightly over many
    iterations; the decode step is the REAL slot_decode_step on the tiny
    model (the smallest — i.e. least favorable — denominator; real
    models are 10-1000x slower per step, the tracer cost is constant)."""
    spec, _ = tiny
    eng = _engine(tiny)
    sched = Scheduler(eng, chunk=8)
    sched.warmup()
    # median UNTRACED decode-step wall over a live request
    req = sched.submit([1, 9, 23], 200, _greedy(spec))
    times = []
    sched.step()  # prefill + first token
    for _ in range(30):
        t0 = time.perf_counter()
        sched.step()
        times.append(time.perf_counter() - t0)
    req.cancel()
    sched.step()
    sched.close()
    step_ms = sorted(times)[len(times) // 2] * 1e3

    # per-iteration tracer cost, tightly measured (enabled path)
    TRACER.configure(capacity=8192, decode_every=1)
    n = 2000
    b = eng.batch
    t0 = time.perf_counter()
    for i in range(n):
        for row in range(b):  # worst case: every row emits an event
            TRACER.event("decode", row + 1, n_out=i)
        TRACER.step(decode_rows=b, prefill_rows=0, chunk=0,
                    queue_depth=0, wall_ms=1.0)
    per_step_ms = (time.perf_counter() - t0) / n * 1e3
    overhead = per_step_ms / step_ms
    assert overhead <= 0.02, (
        f"tracing costs {per_step_ms * 1e3:.1f} us/step = "
        f"{overhead * 100:.2f}% of a {step_ms:.2f} ms decode step")


# -- Prometheus exposition --------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{([^}]*)\})? ([0-9eE.+-]+|NaN)$')
_LABEL_RE = re.compile(r'^[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"$')


def _parse_prometheus(text: str) -> dict:
    """Minimal exposition-format validator: returns {metric: [(labels,
    value)]}; raises AssertionError on format violations scrapers
    reject (sample before HELP/TYPE, duplicate headers, bad labels)."""
    metrics: dict = {}
    seen_meta: dict = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            _, what, name, rest = line.split(" ", 3)
            key = (what, name)
            assert key not in seen_meta, f"duplicate {key}"
            seen_meta[key] = rest
            if what == "TYPE":
                assert rest in ("counter", "gauge", "histogram", "summary")
            continue
        assert not line.startswith("#"), f"stray comment: {line!r}"
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable sample line: {line!r}"
        name, _, labels, value = m.groups()
        base = name
        assert ("TYPE", base) in seen_meta, f"sample before TYPE: {name}"
        for lab in filter(None, (labels or "").split(",")):
            assert _LABEL_RE.match(lab), f"bad label: {lab!r} in {line!r}"
        metrics.setdefault(name, []).append((labels, float(value)))
    return metrics


def test_render_prometheus_supervisor_shape_valid():
    TRACER.configure(capacity=64)
    TRACER.step(decode_rows=2, prefill_rows=1, chunk=8, queue_depth=0,
                wall_ms=3.0)
    summary = {"requests_submitted": 5, "requests_finished": 4,
               "tokens_out": 40, "steps": 33, "state": "ready",
               "ttft_p50_ms": 12.0, "itl_p99_ms": 4.5,
               "mean_slot_occupancy": 1.5, "max_queue_depth": 2,
               "prefix_cache": {"lookups": 4, "hits": 2,
                                "blocks_in_use": 7},
               "resilience": {"crashes": 1, "recoveries": 1,
                              "recovery_p50_ms": 88.0}}
    m = _parse_prometheus(render_prometheus(summary, tracer=TRACER,
                                            model="tiny"))
    assert m["dllama_requests_submitted_total"] == [(None, 5.0)]
    assert m["dllama_prefix_cache_hits_total"] == [(None, 2.0)]
    assert m["dllama_supervisor_crashes_total"] == [(None, 1.0)]
    assert ('state="ready"', 1.0) in m["dllama_state"]
    assert ('state="broken"', 0.0) in m["dllama_state"]
    step = dict(m["dllama_step_ms"])
    assert step[
        'decode_rows="2",prefill_rows="1",chunk="8",quantile="0.5"'] == 3.0


def test_render_prometheus_router_shape_valid():
    summary = {
        "requests_submitted": 9, "state": "ready",
        "router": {"routed": 9, "retries": 1, "failovers_ok": 1,
                   "breaker_trips": 2},
        "replicas": [
            {"replica": 0, "state": "ready", "draining": False,
             "breaker_open": False, "requests_finished": 5,
             "proc": {"exits": 1, "respawns": 1, "spawn_failures": 0,
                      "exit_classes": {"signal:SIGKILL": 1},
                      "respawn_p50_ms": 4300.0}},
            {"replica": 1, "state": "recovering", "draining": True,
             "breaker_open": False, "requests_finished": 4},
        ],
        "cluster": {"pings_sent": 7, "pongs_received": 7,
                    "peers_lost": [{"node_id": 1}]},
    }
    m = _parse_prometheus(render_prometheus(summary, model="tiny",
                                            mode="router"))
    assert dict(m["dllama_replica_up"]) == {'replica="0"': 1.0,
                                            'replica="1"': 0.0}
    assert dict(m["dllama_replica_requests_finished_total"]) == {
        'replica="0"': 5.0, 'replica="1"': 4.0}
    assert m["dllama_router_retries_total"] == [(None, 1.0)]
    assert dict(m["dllama_replica_proc_exit_class_total"]) == {
        'replica="0",class="signal:SIGKILL"': 1.0}
    assert m["dllama_cluster_peers_lost_total"] == [(None, 1.0)]


def test_render_prometheus_cluster_wire_and_sync_families():
    """dlwire (ISSUE 12): the FULL ClusterStats counter set renders as
    tier-invariant dllama_cluster_* families (the old renderer exported
    only 3 of them), the measured wire ledger as
    dllama_wire_{bytes,frames}_total{peer,kind,dir} +
    dllama_heartbeat_rtt_ms{peer} + the clock offset, the startup
    broadcast timings, and the sampled sync/compute split as
    dllama_step_sync_ms / dllama_step_sync_share."""
    summary = {
        "requests_submitted": 1, "state": "ready",
        "cluster": {
            "nnodes": 2, "phase": "decode", "connect_retries": 3,
            "pings_sent": 7, "pongs_received": 6, "pongs_sent": 0,
            "frames_sent": 9, "frames_received": 15,
            "bcast_spec_ms": 12.5, "bcast_tensors_ms": 830.0,
            "bcast_tensors_bytes": 1 << 20,
            "peers_lost": [],
            "wire": {"peers": {"1": {
                "tx": {"PING": {"frames": 7, "bytes": 168},
                       "RUN": {"frames": 2, "bytes": 250}},
                "rx": {"PONG": {"frames": 6, "bytes": 192}},
                "rtt_ms": {"n": 6, "p50_ms": 0.9, "p99_ms": 1.8,
                           "mean_ms": 1.1, "recent": [0.9]},
                "clock_offset_ms": 0.07, "best_rtt_ms": 0.7}}},
        },
        "device_time": {
            "sample_every": 4, "sampled_steps": 3,
            "by_entry": {"slot_decode_step": {"n": 3, "p50_ms": 2.0,
                                              "mean_ms": 2.1}},
            "sync": {"n": 3, "sync_p50_ms": 0.5, "sync_p99_ms": 0.8,
                     "device_p50_ms": 2.0, "sync_share": 0.25},
        },
    }
    m = _parse_prometheus(render_prometheus(summary, model="tiny"))
    # the tier-invariant cluster counter set (satellite: a tier must not
    # lose a family to a launch flag — these were /stats-only before)
    assert m["dllama_cluster_pings_sent_total"] == [(None, 7.0)]
    assert m["dllama_cluster_pongs_received_total"] == [(None, 6.0)]
    assert m["dllama_cluster_pongs_sent_total"] == [(None, 0.0)]
    assert m["dllama_cluster_frames_sent_total"] == [(None, 9.0)]
    assert m["dllama_cluster_frames_received_total"] == [(None, 15.0)]
    assert m["dllama_cluster_connect_retries_total"] == [(None, 3.0)]
    assert m["dllama_cluster_peers_lost_total"] == [(None, 0.0)]
    assert m["dllama_cluster_nnodes"] == [(None, 2.0)]
    assert m["dllama_cluster_phase"] == [('phase="decode"', 1.0)]
    assert dict(m["dllama_cluster_bcast_ms"]) == {'what="spec"': 12.5,
                                                  'what="tensors"': 830.0}
    assert m["dllama_cluster_bcast_bytes_total"] == [('what="tensors"',
                                                      float(1 << 20))]
    # the wire ledger families
    wire = dict(m["dllama_wire_bytes_total"])
    assert wire['peer="1",kind="PING",dir="tx"'] == 168.0
    assert wire['peer="1",kind="RUN",dir="tx"'] == 250.0
    assert wire['peer="1",kind="PONG",dir="rx"'] == 192.0
    frames = dict(m["dllama_wire_frames_total"])
    assert frames['peer="1",kind="PING",dir="tx"'] == 7.0
    rtt = dict(m["dllama_heartbeat_rtt_ms"])
    assert rtt['peer="1",quantile="0.5"'] == 0.9
    assert rtt['peer="1",quantile="0.99"'] == 1.8
    assert m["dllama_cluster_clock_offset_ms"] == [('peer="1"', 0.07)]
    # the sync/compute split (the reference's I/T/S reborn)
    sync = dict(m["dllama_step_sync_ms"])
    assert sync['quantile="0.5"'] == 0.5 and sync['quantile="0.99"'] == 0.8
    assert m["dllama_step_sync_share"] == [(None, 0.25)]


def test_ingest_rebases_cluster_node_spans_onto_one_timeline():
    """A multihost worker's MSG_TRACE span (wall-stamped, shifted by the
    clock-offset estimate at the link layer) merges under the SAME trace
    id as the root's events — by_id serves the linked span the way
    /admin/trace?id= would."""
    TRACER.configure(capacity=256)
    tid = TRACER.new_id()
    TRACER.event("cluster_tick", tid, phase="run", role="root", rank=0)
    # a worker span as multihost._ingest_trace hands it over: ts_wall
    # stamps in the (already offset-corrected) local wall domain
    now_wall = TRACER.to_wall(__import__("time").perf_counter())
    TRACER.ingest([
        {"ts_wall": now_wall + 0.001, "kind": "cluster_tick", "tid": tid,
         "phase": "run", "role": "worker", "rank": 1},
        {"ts_wall": now_wall + 0.050, "kind": "cluster_tick", "tid": tid,
         "phase": "run_done", "role": "worker", "rank": 1, "ms": 49.0},
    ], origin="node1")
    TRACER.event("cluster_lost", tid, node=1, reason="eof", phase="run")
    span = TRACER.by_id(tid)
    assert [e["kind"] for e in span] == ["cluster_tick", "cluster_tick",
                                        "cluster_tick", "cluster_lost"]
    origins = [e.get("origin") for e in span]
    assert origins == [None, "node1", "node1", None]
    # the ingested pair rebased into the LOCAL monotonic domain with
    # their relative spacing preserved (49 ms apart, near "now")
    w0, w1 = span[1]["ts"], span[2]["ts"]
    assert abs((w1 - w0) - 0.049) < 1e-6, (w0, w1)
    local_now = span[3]["ts"]
    assert abs(w0 - local_now) < 1.0, (w0, local_now)


def test_render_prometheus_handles_none_and_idle():
    # legacy / unbuilt tiers: still a valid, scrapeable document
    for mode, st in (("legacy", "off"), ("scheduler", "idle")):
        m = _parse_prometheus(render_prometheus(None, model="x",
                                                mode=mode, state=st))
        assert m["dllama_up"] == [(f'model="x",mode="{mode}"', 1.0)]
        assert (f'state="{st}"', 1.0) in m["dllama_state"]


# -- the HTTP plane: /metrics + /admin/trace across tiers -------------------


def _serve(state):
    from http.server import ThreadingHTTPServer

    from distributed_llama_tpu.apps.api_server import make_handler

    server = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server


def _get(addr, path):
    conn = http.client.HTTPConnection(*addr, timeout=120)
    conn.request("GET", path)
    r = conn.getresponse()
    return r.status, r.getheader("Content-Type") or "", r.read().decode()


@pytest.fixture
def api_state(tiny, tmp_path):
    """ApiState over the synthetic tiny engine (no model file — the
    /metrics plane needs an engine + tokenizer-ish surface only)."""
    from distributed_llama_tpu.apps.api_server import ApiState
    from distributed_llama_tpu.testing import write_fixture
    from distributed_llama_tpu.tokenizer import Tokenizer

    _, tpath = write_fixture(tmp_path, seed=5)
    tokenizer = Tokenizer.from_file(tpath)

    def make(**kw):
        spec, params = tiny
        eng = Engine(spec, params, batch=1, compute_dtype=jnp.float32,
                     cache_dtype=jnp.float32)
        sampler = Sampler(spec.vocab_size, 0.0, 0.9, 3)
        return ApiState(eng, tokenizer, sampler, model_name="tiny", **kw)

    return make


def test_metrics_and_trace_endpoints_all_tiers(api_state, tiny):
    """/metrics answers VALID Prometheus text in the legacy tier, the
    single-supervisor tier, and the thread-router tier (the process
    tier's renderer path is pinned by test_render_prometheus_router_
    shape_valid + the chaos-job test in tests/test_replica_procs.py);
    /admin/trace serves the ring as JSONL behind the admin guard."""
    spec, _ = tiny
    TRACER.configure(capacity=1024)

    # -- legacy tier (no scheduler): process-level series only
    state = api_state()
    srv = _serve(state)
    try:
        code, ctype, body = _get(srv.server_address, "/metrics")
        assert code == 200 and ctype.startswith("text/plain")
        m = _parse_prometheus(body)
        assert ('model="tiny",mode="legacy"', 1.0) in m["dllama_up"]
    finally:
        srv.shutdown()

    # -- supervisor tier: drive one real request, then scrape
    state = api_state(serve_batch=2, serve_chunk=16)
    srv = _serve(state)
    try:
        # idle (front door unbuilt): still valid, mode=scheduler
        m = _parse_prometheus(_get(srv.server_address, "/metrics")[2])
        assert ('state="idle"', 1.0) in m["dllama_state"]

        conn = http.client.HTTPConnection(*srv.server_address, timeout=240)
        conn.request("POST", "/v1/completions",
                     json.dumps({"prompt": "ab", "max_tokens": 4,
                                 "temperature": 0}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        resp.read()
        conn.close()
        code, _, body = _get(srv.server_address, "/metrics")
        m = _parse_prometheus(body)
        assert m["dllama_requests_submitted_total"][0][1] >= 1.0
        assert m["dllama_tokens_out_total"][0][1] >= 1.0
        assert "dllama_step_ms" in m  # the tracer families rode along
        assert ('state="ready"', 1.0) in m["dllama_state"]

        # /admin/trace: loopback passes the guard; JSONL parses; the
        # span view filters by id
        code, ctype, body = _get(srv.server_address, "/admin/trace?n=50")
        assert code == 200 and ctype == "application/x-ndjson"
        lines = [json.loads(ln) for ln in body.splitlines()]
        assert "anchor_wall" in lines[0]
        kinds = {e["kind"] for e in lines[1:]}
        assert {"enqueue", "first_token", "finish", "step"} <= kinds
        tid = next(e["tid"] for e in lines[1:] if e["kind"] == "finish")
        code, _, body = _get(srv.server_address, f"/admin/trace?id={tid}")
        span = [json.loads(ln) for ln in body.splitlines()][1:]
        assert span and all(e["tid"] == tid for e in span)
        assert all("ts_wall" in e for e in span)

        code, _, _ = _get(srv.server_address, "/admin/trace?id=zzz")
        assert code == 400
        # negative n would slice the wrong end of the ring (evs[-n:]
        # == evs[n:] — a near-full dump); it must be a 400 instead
        code, _, _ = _get(srv.server_address, "/admin/trace?n=-5")
        assert code == 400
    finally:
        srv.shutdown()
        if state._scheduler is not None:
            state._scheduler.close()

    # -- thread-router tier: per-replica series
    state = api_state(serve_batch=2, serve_chunk=16, replicas=2)
    srv = _serve(state)
    try:
        # idle scrape BEFORE any traffic: mode comes from the config,
        # not the lazily-built front door — the label must not flip
        # from "scheduler" to "router" after the first request
        m = _parse_prometheus(_get(srv.server_address, "/metrics")[2])
        assert ('model="tiny",mode="router"', 1.0) in m["dllama_up"]
        assert ('state="idle"', 1.0) in m["dllama_state"]
        conn = http.client.HTTPConnection(*srv.server_address, timeout=240)
        conn.request("POST", "/v1/completions",
                     json.dumps({"prompt": "ab", "max_tokens": 3,
                                 "temperature": 0}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        resp.read()
        conn.close()
        code, _, body = _get(srv.server_address, "/metrics")
        m = _parse_prometheus(body)
        assert dict(m["dllama_replica_up"]) == {'replica="0"': 1.0,
                                                'replica="1"': 1.0}
        assert m["dllama_router_routed_total"][0][1] >= 1.0
        assert ('model="tiny",mode="router"', 1.0) in m["dllama_up"]
    finally:
        srv.shutdown()
        if state._scheduler is not None:
            state._scheduler.close()


def test_stats_and_metrics_carry_wire_plane_with_live_link(api_state):
    """With a cluster link installed, /stats hoists the measured wire
    ledger as its own `wire` block and /metrics renders the
    dllama_cluster_* + dllama_wire_* families — in the LEGACY tier too
    (tier-invariance satellite: the cluster plane must not vanish off a
    launch flag)."""
    from distributed_llama_tpu.parallel import multihost as mh

    link = mh.WorkerLink("127.0.0.1", 1, 1, 2)
    link._init_stats(connect_retries=2)
    link.stats.pongs_sent = 5
    link.stats.wire.account(0, "PING", "rx", 160, frames=5)
    link.stats.wire.account(0, "PONG", "tx", 160, frames=5)
    old = mh.get_link()
    mh.set_link(link)
    state = api_state()  # legacy tier: serve_batch off
    srv = _serve(state)
    try:
        code, _, body = _get(srv.server_address, "/stats")
        assert code == 200
        payload = json.loads(body)
        assert payload["cluster"]["pongs_sent"] == 5
        wire = payload["wire"]  # the hoisted block
        assert wire["peers"]["0"]["tx"]["PONG"]["bytes"] == 160
        assert wire["rx_bytes"] == 160

        m = _parse_prometheus(_get(srv.server_address, "/metrics")[2])
        assert m["dllama_cluster_pongs_sent_total"] == [(None, 5.0)]
        assert m["dllama_cluster_connect_retries_total"] == [(None, 2.0)]
        assert dict(m["dllama_wire_bytes_total"])[
            'peer="0",kind="PONG",dir="tx"'] == 160.0
    finally:
        srv.shutdown()
        mh.set_link(old)


def test_admin_trace_404_when_tracing_off(api_state):
    assert not TRACER.enabled
    state = api_state(serve_batch=2)
    srv = _serve(state)
    try:
        code, _, body = _get(srv.server_address, "/admin/trace")
        assert code == 404 and "--trace" in body
        # /metrics still answers without the tracer families
        code, _, body = _get(srv.server_address, "/metrics")
        assert code == 200
        assert "dllama_step_ms" not in body
    finally:
        srv.shutdown()
        if state._scheduler is not None:
            state._scheduler.close()


def test_admin_trace_kind_and_since_filters(api_state, tiny):
    """ISSUE 10 satellite: GET /admin/trace grows kind= and since_ms=
    filters alongside n=/id= — validated (400 on garbage), and the kind
    filter scans the WHOLE ring before tailing n (a sparse kind must not
    vanish behind n pre-filter events)."""
    TRACER.configure(capacity=2048)
    state = api_state(serve_batch=2, serve_chunk=16)
    srv = _serve(state)
    try:
        conn = http.client.HTTPConnection(*srv.server_address, timeout=240)
        conn.request("POST", "/v1/completions",
                     json.dumps({"prompt": "ab", "max_tokens": 4,
                                 "temperature": 0}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        resp.read()
        conn.close()

        # kind=: only that kind comes back — here 'finish', which sits
        # behind many decode/step events (n=3 unfiltered would miss it)
        code, _, body = _get(srv.server_address,
                             "/admin/trace?kind=finish&n=3")
        assert code == 200
        evs = [json.loads(ln) for ln in body.splitlines()][1:]
        assert evs and all(e["kind"] == "finish" for e in evs)

        # since_ms=: a large window keeps everything, a zero window
        # keeps (effectively) nothing
        code, _, body = _get(srv.server_address,
                             "/admin/trace?since_ms=600000")
        assert code == 200
        recent = [json.loads(ln) for ln in body.splitlines()][1:]
        assert recent
        code, _, body = _get(srv.server_address, "/admin/trace?since_ms=0")
        assert code == 200
        assert len([json.loads(ln) for ln in body.splitlines()][1:]) <= 1

        # filters compose with id=
        tid = next(e["tid"] for e in recent if e["kind"] == "finish")
        code, _, body = _get(srv.server_address,
                             f"/admin/trace?id={tid}&kind=prefill")
        span = [json.loads(ln) for ln in body.splitlines()][1:]
        assert span and all(e["kind"] == "prefill" and e["tid"] == tid
                            for e in span)

        # validation: garbage is a 400, never an empty-but-200 dump
        for q in ("kind=notakind", "kind=", "since_ms=abc",
                  "since_ms=-1", "since_ms=nan"):
            code, _, _ = _get(srv.server_address, f"/admin/trace?{q}")
            assert code == 400, q
    finally:
        srv.shutdown()
        if state._scheduler is not None:
            state._scheduler.close()
