"""Fixed-seed end-to-end determinism — the reference's examples/macbeth.sh
(fixed seed/temp/topp, generated transcript string-compared against a stored
one). Here: a fixed-seed Q40 fixture model written to `.m`, generated with
the xorshift sampler at temperature 0.7, asserted against the pinned token
sequence; plus CLI-level run-to-run equality.

The pinned sequence is CPU-f32 (tests run on the virtual CPU mesh via
conftest.py); like the reference's transcript it is platform-pinned — the
reference notes its macbeth output is machine-dependent too.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from distributed_llama_tpu.apps import dllama
from distributed_llama_tpu.io import model_tensor_plan, write_model, \
    write_tokenizer_file, TokenizerData
from distributed_llama_tpu.io.model_file import read_model
from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.models.params import load_params
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.runtime import Engine
from distributed_llama_tpu.sampler import Sampler

# generated once from this exact fixture (seed 1234 weights, sampler seed
# 4242, temp 0.7, topp 0.9, prompt [1, 65, 66, 67]) — any change to the Q40
# codec, forward math, sampler RNG, or file round-trip shows up here
GOLDEN_TOKENS = [218, 272, 162, 212, 265, 102, 104, 77, 108, 130, 29, 157,
                 135, 238, 90, 251, 10, 77, 59, 7, 161, 235, 69, 87]


def _spec():
    return ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=288, seq_len=192,
                     hidden_act=HiddenAct.SILU,
                     weights_float_type=FloatType.Q40)


def _write_fixture(tmp_path):
    spec = _spec()
    rng = np.random.default_rng(1234)
    tensors = {name: rng.standard_normal(shape).astype(np.float32) * 0.05
               for name, shape, _ in model_tensor_plan(spec)}
    mpath = str(tmp_path / "model.m")
    write_model(mpath, spec, tensors)

    vocab = [b"<unk>", b"<s>", b"</s>"]
    vocab += [f"<0x{b:02X}>".encode() for b in range(256)]
    while len(vocab) < spec.vocab_size:
        vocab.append(f"<fill{len(vocab)}>".encode())
    tpath = str(tmp_path / "tok.t")
    write_tokenizer_file(tpath, TokenizerData(
        vocab=vocab, scores=[0.0] * len(vocab), bos_id=1, eos_id=2))
    return mpath, tpath


def test_fixed_seed_token_transcript(tmp_path):
    """The macbeth check: full token sequence equality against the pinned
    transcript (ref: examples/macbeth.sh)."""
    mpath, _ = _write_fixture(tmp_path)
    spec, host = read_model(mpath)
    params = load_params(spec, host, mode="q40", dtype=jnp.float32)
    eng = Engine(spec, params, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    sampler = Sampler(spec.vocab_size, temperature=0.7, topp=0.9, seed=4242)
    res = eng.generate([1, 65, 66, 67], max_tokens=24, sampler=sampler)
    assert res.tokens == GOLDEN_TOKENS


def test_cli_run_to_run_deterministic(tmp_path, capsys):
    """Full CLI path: two runs with the same seed print identical output
    (and a different seed diverges)."""
    mpath, tpath = _write_fixture(tmp_path)
    argv = ["generate", "--model", mpath, "--tokenizer", tpath,
            "--prompt", "ABC", "--steps", "16", "--temperature", "0.7",
            "--compute-dtype", "f32", "--cache-dtype", "f32"]
    dllama.main(argv + ["--seed", "4242"])
    out1 = capsys.readouterr().out
    dllama.main(argv + ["--seed", "4242"])
    out2 = capsys.readouterr().out
    assert out1 == out2
    dllama.main(argv + ["--seed", "77"])
    out3 = capsys.readouterr().out
    assert out3 != out1
