"""Model/tokenizer file format round-trip tests (format parity with the
reference `.m`/`.t` layouts — ref: src/transformer.cpp:183-291,623-683,
src/tokenizer.cpp:38-80)."""

import struct

import numpy as np
import pytest

from distributed_llama_tpu.io import (
    TokenizerData,
    read_model,
    read_spec,
    read_tokenizer_file,
    write_model,
    write_tokenizer_file,
    model_tensor_plan,
)
from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.quants import FloatType


def tiny_spec(arch=ArchType.LLAMA, wt=FloatType.F32, **kw):
    base = dict(
        arch=arch, dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
        vocab_size=96, seq_len=32, hidden_act=HiddenAct.SILU, rope_theta=10000.0,
        weights_float_type=wt,
    )
    if arch in (ArchType.MIXTRAL, ArchType.GROK1):
        base.update(n_experts=4, n_active_experts=2)
    base.update(kw)
    return ModelSpec(**base)


def random_tensors(spec, rng):
    return {
        name: rng.standard_normal(shape).astype(np.float32) * 0.05
        for name, shape, _ in model_tensor_plan(spec)
    }


@pytest.mark.parametrize("arch", [ArchType.LLAMA, ArchType.MIXTRAL, ArchType.GROK1])
@pytest.mark.parametrize("wt", [FloatType.F32, FloatType.Q40])
def test_model_roundtrip(tmp_path, rng, arch, wt):
    spec = tiny_spec(arch=arch, wt=wt)
    tensors = random_tensors(spec, rng)
    path = str(tmp_path / "model.m")
    write_model(path, spec, tensors)

    spec2 = read_spec(path)
    assert spec2.arch == arch
    assert spec2.dim == spec.dim
    assert spec2.weights_float_type == wt
    assert spec2.kv_dim == spec.kv_dim

    _, loaded = read_model(path)
    for name, shape, ftype in model_tensor_plan(spec):
        got = loaded[name].to_f32()
        want = tensors[name]
        if ftype == FloatType.F32:
            np.testing.assert_array_equal(got, want)
        else:
            # Q40: 4-bit round-trip tolerance — the asymmetric +8.5/clamp-15
            # encoder (converter/writer.py:37-38) loses up to 1.5*scale on
            # the value opposite the max-magnitude one
            bound = np.abs(want.reshape(-1, 32)).max(axis=-1) * (1.5 / 8.0) + 1e-5
            err = np.abs((got - want).reshape(-1, 32))
            assert (err <= bound[:, None]).all()


def test_header_bytes_match_reference_layout(tmp_path, rng):
    """First 8 bytes: KV magic + header size (ref: converter/writer.py:127-137)."""
    spec = tiny_spec()
    path = str(tmp_path / "m.m")
    write_model(path, spec, random_tensors(spec, rng))
    raw = open(path, "rb").read(8)
    magic, header_size = struct.unpack("<ii", raw)
    assert magic == 0xA00ABCD
    assert header_size == 8 + 14 * 8  # 14 KV pairs


def test_legacy_header(tmp_path):
    """Old fixed-struct header (ref: src/transformer.cpp:198-213)."""
    path = str(tmp_path / "legacy.m")
    vals = dict(dim=64, hidden_dim=128, n_layers=1, n_heads=4, n_kv_heads=4,
                n_experts=0, n_active_experts=0, vocab_size=32, max_seq_len=16)
    with open(path, "wb") as f:
        f.write(struct.pack("<i", 0xABCD00))
        f.write(struct.pack("<9i", *vals.values()))
    spec = read_spec(path, weights_float_type=FloatType.F32)
    assert spec.arch == ArchType.LLAMA
    assert spec.dim == 64 and spec.seq_len == 16
    assert spec.rope_theta == 10000.0


def test_tokenizer_file_roundtrip(tmp_path):
    data = TokenizerData(
        vocab=[b"<unk>", b"<s>", b"</s>", b" ", b"a", b"b", b"ab", b" ab"],
        scores=[0.0, 0.0, 0.0, -1.0, -2.0, -3.0, -0.5, -0.2],
        bos_id=1, eos_id=2,
    )
    path = str(tmp_path / "tok.t")
    write_tokenizer_file(path, data)
    got = read_tokenizer_file(path)
    assert got.vocab == data.vocab
    assert got.bos_id == 1 and got.eos_id == 2 and got.pad_id == -1
    np.testing.assert_allclose(got.scores, data.scores)
    # header layout parity: 24 bytes, magic first (ref: src/tokenizer.hpp:16-23)
    raw = open(path, "rb").read(24)
    assert struct.unpack("<I", raw[:4])[0] == 0x567123
