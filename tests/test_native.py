"""Native C++ tokenizer/sampler parity vs the pure-Python oracles.

The reference ships tokenizer + sampler as C++ (ref: src/tokenizer.cpp);
native/dllama_native.cpp restores that layering, and these tests pin its
behavior to the Python implementations byte-for-byte / index-for-index.
Skipped when the shared library has not been built (`make -C native`).
"""

import numpy as np
import pytest

from distributed_llama_tpu import native
from distributed_llama_tpu.io.tokenizer_file import TokenizerData
from distributed_llama_tpu.sampler import Sampler
from distributed_llama_tpu.tokenizer import Tokenizer

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library not built (make -C native)")


def _tok_data():
    # small BPE-ish vocab with merges, byte-fallback pieces, and a dup piece
    vocab = [b"<unk>", b"<s>", b"</s>"]
    vocab += [f"<0x{b:02X}>".encode() for b in range(256)]
    vocab += [b" ", b"a", b"b", b"ab", b" a", b"ba", b"bab", b" hello",
              b"he", b"ll", b"o", b"hell", b"ab"]  # trailing dup of "ab"
    scores = [0.0] * 259 + [1.0, 1.1, 1.2, 5.0, 2.0, 4.0, 6.0, 9.0, 3.0,
                            3.5, 1.05, 7.0, 8.0]
    return TokenizerData(vocab=vocab, scores=scores, bos_id=1, eos_id=2)


def test_native_tokenizer_matches_python():
    data = _tok_data()
    py = Tokenizer(data, backend="python")
    nat = Tokenizer(data, backend="native")
    assert nat._native is not None and py._native is None

    cases = ["", "a", "ab", "bab", " hello", "hello ab",
             "abba abab", "héllo \N{SNOWMAN}", "\x00\x7f", "a" * 64]
    for text in cases:
        for add_bos in (True, False):
            assert nat.encode(text, add_bos=add_bos) == \
                py.encode(text, add_bos=add_bos), text
    # duplicate piece: first occurrence must win in both
    assert nat.encode("ab", add_bos=False) == py.encode("ab", add_bos=False)

    # decode parity incl. bos space-strip and raw-byte pieces
    ids = py.encode("hello ab", add_bos=True)
    for prev, tok in zip([py.bos_id] + ids, ids):
        assert nat.decode_piece(prev, tok) == py.decode_piece(prev, tok)
    assert nat.decode_piece(5, 3 + 0x41) == b"\x41"  # <0x41> raw byte


def test_native_tokenizer_fuzz_parity():
    data = _tok_data()
    py = Tokenizer(data, backend="python")
    nat = Tokenizer(data, backend="native")
    rng = np.random.default_rng(7)
    alphabet = list("ab hello") + ["é", "√", "\n"]
    for _ in range(50):
        s = "".join(rng.choice(alphabet)
                    for _ in range(int(rng.integers(0, 40))))
        assert nat.encode(s) == py.encode(s), repr(s)


def test_native_sampler_matches_python():
    rng = np.random.default_rng(3)
    for temp, topp in [(0.0, 0.9), (0.8, 0.0), (0.7, 0.9), (1.3, 0.5)]:
        py = Sampler(100, temp, topp, seed=123, backend="python")
        nat = native.NativeSampler(100, temp, topp, seed=123)
        for i in range(50):
            logits = rng.standard_normal(100).astype(np.float32) * 3
            a = py.sample(logits.copy())
            b = nat.sample(logits.copy())
            assert a == b, (temp, topp, i)
        assert py.rng_state == nat.rng_state  # identical xorshift streams


def test_native_sampler_state_roundtrip():
    nat = native.NativeSampler(50, 0.8, 0.9, seed=9)
    logits = np.random.default_rng(0).standard_normal(50).astype(np.float32)
    saved = nat.rng_state
    a = nat.sample(logits.copy())
    nat.rng_state = saved
    assert nat.sample(logits.copy()) == a
    nat.set_temp(0.0)
    assert nat.sample(logits.copy()) == int(np.argmax(logits))
