"""tools/dlprof.py — the offline capacity analyzer: knee math, span
decomposition, timeline merging (worker prefixes), the end-to-end path
over a REAL scheduler's --trace-dir sink, and the CLI smoke the CI main
matrix runs (--selftest). The BENCH_SERVE=1 artifact acceptance bar
(reproduce the curve from a real bench row's step_timeline) rides
tests/test_bench_outage.py::test_serve_row_emits_valid_json, which
already pays for the bench subprocess."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import dlprof  # noqa: E402


# -- knee math --------------------------------------------------------------


def test_knee_on_a_saturating_curve():
    # linear ms growth past 4 rows: marginal throughput collapses there
    curve = [(1, 5.0), (2, 5.2), (4, 5.9), (8, 14.0), (16, 30.0)]
    k = dlprof.knee_estimate(curve)
    assert k["knee_rows"] == 4
    assert k["method"] == "marginal_throughput"
    assert len(k["curve"]) == 5


def test_knee_without_saturation_recommends_measuring_higher():
    curve = [(1, 5.0), (2, 5.1), (4, 5.3)]  # still nearly flat
    k = dlprof.knee_estimate(curve)
    assert k["knee_rows"] == 4
    assert k["method"] == "no_saturation_observed"
    assert "larger batches" in k["note"]


def test_knee_single_point_and_empty():
    assert dlprof.knee_estimate([]) is None
    k = dlprof.knee_estimate([(2, 6.0)])
    assert k["knee_rows"] == 2 and k["method"] == "single_point"


def test_recommendation_caps_at_hbm_headroom():
    k = dlprof.knee_estimate([(1, 5.0), (2, 5.2), (4, 5.9), (8, 14.0)])
    assert k["knee_rows"] == 4
    rec = dlprof.serve_batch_recommendation(
        k, {"slots_addable": 0})          # no headroom past measured max
    assert rec["serve_batch"] == 4        # knee under the cap: unchanged
    rec = dlprof.serve_batch_recommendation(k, {"slots_addable": None})
    assert rec["serve_batch"] == 4 and rec["hbm_cap_rows"] is None


# -- timeline merging -------------------------------------------------------


def test_merge_strips_worker_prefixes_and_prefers_larger_n():
    events = [{"kind": "step", "dec": 2, "pre": 0, "chunk": 0, "ms": 7.0}]
    rows = [{"step_timeline": {
        "r0_dec2_pre0_c0": {"n": 50, "p50_ms": 6.5, "p99_ms": 7.0,
                            "mean_ms": 6.6},
        "dec4_pre1_c16": {"n": 3, "p50_ms": 9.0, "p99_ms": 9.5,
                          "mean_ms": 9.1},
        "not_a_key": {"n": 1}}}]
    tl = dlprof.merge_timelines(events, rows)
    assert (2, 0, 0) in tl and (4, 1, 16) in tl
    assert tl[(2, 0, 0)]["n"] == 50      # bench summary outweighs 1 event
    assert (0, 0, 0) not in tl
    assert dlprof.decode_curve(tl) == [(2, 6.5)]  # prefill row excluded


# -- span decomposition -----------------------------------------------------


def _span(tid=7, error=False):
    t = 100.0
    evs = [{"ts_wall": t, "kind": "enqueue", "tid": tid, "n_prompt": 9},
           {"ts_wall": t + 0.004, "kind": "route", "tid": tid,
            "replica": 0},
           {"ts_wall": t + 0.005, "kind": "admit", "tid": tid,
            "queue_ms": 5.0},
           {"ts_wall": t + 0.006, "kind": "seed", "tid": tid, "hit": 4},
           {"ts_wall": t + 0.030, "kind": "first_token", "tid": tid,
            "ttft_ms": 30.0}]
    if error:
        evs.append({"ts_wall": t + 0.050, "kind": "error", "tid": tid,
                    "code": "replica_lost", "n_out": 2})
    else:
        evs.append({"ts_wall": t + 0.090, "kind": "finish", "tid": tid,
                    "reason": "length", "n_out": 7})
    return evs


def test_critical_path_decomposes_phases():
    p = dlprof.critical_path(_span())
    assert p["status"] == "length" and p["n_out"] == 7
    assert p["queue_ms"] == 5.0 and p["seed_hit"] == 4
    assert p["ttft_ms"] == 30.0
    assert abs(p["prefill_ms"] - 25.0) < 0.5    # admit -> first token
    assert abs(p["decode_ms"] - 60.0) < 0.5
    assert abs(p["total_ms"] - 90.0) < 0.5
    assert p["itl_ms"] == pytest.approx(10.0, abs=0.5)
    assert p["dominant_phase"] == "decode"


def test_critical_path_error_span_and_unterminated():
    p = dlprof.critical_path(_span(error=True))
    assert p["status"] == "error:replica_lost" and p["n_out"] == 2
    assert dlprof.critical_path(_span()[:3]) is None  # no terminal


def test_goodput_splits_on_slo():
    paths = [dlprof.critical_path(_span(tid=t)) for t in (1, 2)]
    events = _span(1) + _span(2)
    g = dlprof.goodput(paths, events, slo_ttft_ms=500.0, slo_itl_ms=100.0)
    assert g["within_slo"] == 2 and g["slo_fraction"] == 1.0
    g = dlprof.goodput(paths, events, slo_ttft_ms=10.0, slo_itl_ms=100.0)
    assert g["within_slo"] == 0  # ttft 30 ms misses a 10 ms SLO


# -- the wire report (dlwire) -----------------------------------------------


def test_wire_report_merges_ledgers_sync_and_reconciles():
    """wire_report: bench rows' wire blocks (both the {root,worker}
    bench-row shape and a raw WireStats summary) merge into per-peer
    totals; `sync` trace events yield the window-sum share; every
    reconcile entry is collected with the drift flag re-derived at the
    25% bar."""
    row = {"wire": {
        "root": {"peers": {"1": {
            "tx": {"PING": {"frames": 4, "bytes": 96},
                   "RUN": {"frames": 1, "bytes": 120}},
            "rx": {"PONG": {"frames": 4, "bytes": 128}},
            "rtt_ms": {"n": 4, "p50_ms": 1.0, "p99_ms": 2.0,
                       "mean_ms": 1.2},
            "clock_offset_ms": 0.1}}},
        "worker": {"peers": {"0": {
            "rx": {"PING": {"frames": 4, "bytes": 96}}}}},
        "reconcile": {"measured": 120.0, "modeled": 120.0,
                      "unit": "bytes", "drift_frac": 0.0}}}
    raw = {"wire": {"peers": {"2": {
        "tx": {"RUN": {"frames": 1, "bytes": 50}}}}}}
    sync_events = [{"kind": "sync", "tid": 0, "ts_wall": 1.0 + i,
                    "sync_ms": 1.0, "device_ms": 4.0} for i in range(3)]
    w = dlprof.wire_report(sync_events, [row, raw])
    assert w["peers"]["root:peer1"]["tx_bytes"] == 216
    assert w["peers"]["root:peer1"]["rtt_ms"]["p99_ms"] == 2.0
    assert w["peers"]["worker:peer0"]["rx_bytes"] == 96
    assert w["peers"]["peer2"]["tx_bytes"] == 50
    assert w["sync"] == {"sampled_steps": 3, "sync_p50_ms": 1.0,
                         "sync_p99_ms": 1.0, "device_p50_ms": 4.0,
                         "sync_share": 0.25}
    assert len(w["reconcile"]) == 1 and not w["drift"]

    # a stale artifact whose producer never flagged: the report
    # re-derives drift at its own bar (0.3 >= 0.25 -> flagged)
    stale = {"wire": {"reconcile": {"measured": 130.0, "modeled": 100.0,
                                    "drift_frac": 0.3}}}
    w2 = dlprof.wire_report([], [stale])
    assert w2["drift"] and w2["reconcile"][0]["drift"] is True

    # no wire data anywhere: the section is honestly absent
    assert dlprof.wire_report([], [{"metric": "x"}]) is None
    r = dlprof.analyze([], [{"metric": "x"}], wire=True)
    assert r["wire"] is None and "Wire" not in dlprof.render_markdown(r)


def test_wire_markdown_renders_peer_table_and_flags():
    row = {"wire": {"peers": {"1": {
        "tx": {"RUN": {"frames": 2, "bytes": 250}},
        "rtt_ms": {"n": 5, "p50_ms": 0.9, "p99_ms": 1.8, "mean_ms": 1.1},
        "clock_offset_ms": 0.07}},
        "reconcile": {"measured": 140.0, "modeled": 100.0,
                      "unit": "bytes", "drift_frac": 0.4}}}
    report = dlprof.analyze(
        [{"kind": "sync", "tid": 0, "ts_wall": 1.0, "sync_ms": 2.0,
          "device_ms": 10.0}], [row], wire=True)
    md = dlprof.render_markdown(report)
    assert "## Wire (measured cluster plane)" in md
    assert "| peer1 | 250 |" in md
    assert "0.9/1.8" in md
    assert "share 0.2" in md
    assert "DRIFTED" in md


# -- end to end over a REAL scheduler trace ---------------------------------


def test_analyze_real_trace_dir_end_to_end(tmp_path):
    """Drive the real scheduler with a --trace-dir sink, then run the
    analyzer over the JSONL it wrote: spans decompose, the step curve
    has decode compositions, the knee is non-null."""
    jnp = pytest.importorskip("jax.numpy")
    from distributed_llama_tpu.models import (ArchType, HiddenAct,
                                              ModelSpec)
    from distributed_llama_tpu.models.params import (load_params,
                                                     random_tensors)
    from distributed_llama_tpu.runtime.engine import Engine
    from distributed_llama_tpu.runtime.scheduler import Scheduler
    from distributed_llama_tpu.runtime.trace import TRACER
    from distributed_llama_tpu.sampler import Sampler

    spec = ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=128,
                     seq_len=64, hidden_act=HiddenAct.SILU)
    params = load_params(spec, random_tensors(spec, seed=3, scale=0.05),
                         mode="dense", dtype=jnp.float32)
    sink = str(tmp_path / "trace")
    TRACER.reset()
    TRACER.configure(capacity=4096, sink_dir=sink, decode_every=2)
    try:
        eng = Engine(spec, params, batch=2, compute_dtype=jnp.float32,
                     cache_dtype=jnp.float32)
        sched = Scheduler(eng, chunk=8)
        reqs = [sched.submit([1, 9, 23, 54, 7, 11, 40, 3, 15], 6,
                             Sampler(128, 0.0, 0.9, 1))
                for _ in range(2)]
        while not all(r.finished.is_set() for r in reqs):
            sched.step()
        for r in reqs:
            assert len(list(r.tokens(timeout=10.0))) == 6
        sched.close()
    finally:
        TRACER.reset()  # closes (flushes) the sink

    events = dlprof.load_trace_dir(sink)
    assert events, "sink wrote nothing"
    report = dlprof.analyze(events)
    assert report["requests"]["requests"] == 2
    assert report["requests"]["completed"] == 2
    assert report["requests"]["ttft_ms"]["p50"] > 0
    assert report["step_curve"]["decode_points"], report["step_curve"]
    assert report["step_curve"]["knee"] is not None
    assert report["step_curve"]["knee"]["knee_rows"] >= 1
    assert report["goodput"]["completed"] == 2
    assert report["tail"] and report["tail"][0]["dominant_phase"]
    json.dumps(report)
    md = dlprof.render_markdown(report)
    assert "# dlprof report" in md and "Knee:" in md


# -- the CLI ----------------------------------------------------------------


def test_cli_selftest_subprocess():
    """The exact invocation the CI `dlprof smoke` step runs."""
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "dlprof.py"),
                        "--selftest"],
                       capture_output=True, text=True, timeout=120,
                       cwd=REPO)
    assert r.returncode == 0, r.stderr
    assert "OK" in r.stdout


def test_cli_writes_report_files(tmp_path):
    trace = tmp_path / "t"
    trace.mkdir()
    with open(trace / "trace-00000001.jsonl", "w") as f:
        for e in _span() + [{"ts_wall": 101.0, "kind": "step", "tid": 0,
                             "dec": 2, "pre": 0, "chunk": 0, "ms": 6.0}]:
            f.write(json.dumps(e) + "\n")
    out = str(tmp_path / "report")
    rc = dlprof.main(["--trace-dir", str(trace), "--out", out])
    assert rc == 0
    with open(out + ".json") as f:
        rep = json.load(f)
    assert rep["requests"]["requests"] == 1
    assert rep["step_curve"]["knee"]["knee_rows"] == 2
    assert os.path.exists(out + ".md")
