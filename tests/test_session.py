"""KV-cache session persistence (Engine.save_session / load_session).

Net-new vs the reference, which has no cache persistence or session resume
(SURVEY.md §5.4 — its API server restarts generation state per request): a
restored session must continue a generation exactly where the original
engine would have.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llama_tpu.models import ArchType
from distributed_llama_tpu.models.params import load_params
from distributed_llama_tpu.parallel import make_mesh
from distributed_llama_tpu.runtime import Engine
from distributed_llama_tpu.sampler import Sampler

from test_model_forward import make_spec, dense_weights


def greedy(v=128):
    return Sampler(v, temperature=0.0, topp=0.9, seed=1)


def _spec_host(seed=51, **kw):
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=64, **kw)
    host, _ = dense_weights(spec, seed=seed)
    return spec, host


@pytest.mark.parametrize("cache_dtype", [jnp.float32, jnp.bfloat16])
def test_session_roundtrip_continues_exactly(tmp_path, cache_dtype):
    spec, host = _spec_host()
    params = load_params(spec, host, mode="q40", dtype=jnp.float32)
    prompt = [1, 5, 9, 2]

    eng_a = Engine(spec, params, compute_dtype=jnp.float32,
                   cache_dtype=cache_dtype)
    part1 = eng_a.generate(prompt, 5, greedy()).tokens
    eng_a.save_session(str(tmp_path / "s.npz"))
    want = eng_a.generate([part1[-1]], 5, greedy()).tokens

    eng_b = Engine(spec, params, compute_dtype=jnp.float32,
                   cache_dtype=cache_dtype)
    eng_b.load_session(str(tmp_path / "s.npz"))
    assert eng_b.pos == len(prompt) + len(part1) - 1
    got = eng_b.generate([part1[-1]], 5, greedy()).tokens
    assert got == want, (got, want)


def test_session_token_history_roundtrips(tmp_path):
    """The optional token history rides along with the cache (the chat CLI
    uses it to keep mining speculative drafts across restarts); files saved
    without one load as []."""
    spec, host = _spec_host()
    params = load_params(spec, host, mode="q40", dtype=jnp.float32)
    eng = Engine(spec, params, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    out = eng.generate([1, 5], 3, greedy()).tokens
    eng.save_session(str(tmp_path / "s.npz"), tokens=[1, 5] + out)
    eng.save_session(str(tmp_path / "bare.npz"))

    eng2 = Engine(spec, params, compute_dtype=jnp.float32,
                  cache_dtype=jnp.float32)
    assert eng2.load_session(str(tmp_path / "s.npz")) == [1, 5] + out
    assert eng2.load_session(str(tmp_path / "bare.npz")) == []

    # PRE-change session files have no 'tokens' key at all — rewrite one
    # without it and assert the fallback branch still returns []
    z = np.load(str(tmp_path / "bare.npz"))
    legacy = {k: z[k] for k in z.files if k != "tokens"}
    with open(str(tmp_path / "legacy.npz"), "wb") as f:
        np.savez(f, **legacy)
    assert eng2.load_session(str(tmp_path / "legacy.npz")) == []


def test_session_extensionless_path_roundtrips(tmp_path):
    """np.savez appends '.npz' to extension-less str paths; save_session
    must write EXACTLY the requested path or chat --session silently never
    resumes (the resume check uses the raw path)."""
    import os

    spec, host = _spec_host()
    params = load_params(spec, host, mode="q40", dtype=jnp.float32)
    eng = Engine(spec, params, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    eng.generate([1, 5], 2, greedy())
    path = str(tmp_path / "chat.sess")
    eng.save_session(path)
    assert os.path.exists(path), os.listdir(tmp_path)
    eng2 = Engine(spec, params, compute_dtype=jnp.float32,
                  cache_dtype=jnp.float32)
    eng2.load_session(path)
    assert eng2.pos == eng.pos


def test_session_rejects_mismatched_config(tmp_path):
    spec, host = _spec_host()
    params = load_params(spec, host, mode="q40", dtype=jnp.float32)
    eng = Engine(spec, params, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    eng.generate([1, 5], 2, greedy())
    eng.save_session(str(tmp_path / "s.npz"))

    other_spec, other_host = _spec_host(n_layers=4)
    other = Engine(other_spec,
                   load_params(other_spec, other_host, mode="q40",
                               dtype=jnp.float32),
                   compute_dtype=jnp.float32, cache_dtype=jnp.float32)
    with pytest.raises(ValueError, match="does not match"):
        other.load_session(str(tmp_path / "s.npz"))
    # dtype mismatch is a config mismatch too (bit patterns differ)
    f8 = Engine(spec, params, compute_dtype=jnp.float32,
                cache_dtype=jnp.bfloat16)
    with pytest.raises(ValueError, match="does not match"):
        f8.load_session(str(tmp_path / "s.npz"))


def test_session_rejects_different_weight_content(tmp_path):
    """A same-shape model with different weights (fine-tune, requant) must
    be refused: its KV cache never came from the loaded weights (ADVICE
    r3). build_engine passes the model file's content fingerprint; two
    different files yield different fingerprints."""
    spec, host = _spec_host()
    params = load_params(spec, host, mode="q40", dtype=jnp.float32)
    eng = Engine(spec, params, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32, model_fingerprint=0xAAAA)
    eng.generate([1, 5], 2, greedy())
    eng.save_session(str(tmp_path / "s.npz"))

    tuned = Engine(spec, params, compute_dtype=jnp.float32,
                   cache_dtype=jnp.float32, model_fingerprint=0xBBBB)
    with pytest.raises(ValueError, match="does not match"):
        tuned.load_session(str(tmp_path / "s.npz"))

    same = Engine(spec, params, compute_dtype=jnp.float32,
                  cache_dtype=jnp.float32, model_fingerprint=0xAAAA)
    assert same.load_session(str(tmp_path / "s.npz")) == []
    assert same.pos == eng.pos

    # fingerprint 0 = unknown weights (in-memory params): degrades to the
    # shape-only check instead of refusing every CLI-saved session
    unknown = Engine(spec, params, compute_dtype=jnp.float32,
                     cache_dtype=jnp.float32)
    assert unknown.load_session(str(tmp_path / "s.npz")) == []


def test_content_fingerprint_distinguishes_files(tmp_path):
    from distributed_llama_tpu.io.model_file import content_fingerprint

    a, b = tmp_path / "a.bin", tmp_path / "b.bin"
    a.write_bytes(b"\x01" * 100_000)
    b.write_bytes(b"\x01" * 99_999 + b"\x02")  # same size, one byte off
    assert content_fingerprint(str(a)) != content_fingerprint(str(b))
    assert content_fingerprint(str(a)) == content_fingerprint(str(a))


def test_session_restores_onto_mesh(tmp_path):
    """A session saved on a single device restores onto a tp mesh (the
    cache re-places with the engine's sharding) and continues exactly."""
    spec, host = _spec_host()
    params = load_params(spec, host, mode="q40", dtype=jnp.float32)
    prompt = [1, 5, 9, 2]

    eng_a = Engine(spec, params, compute_dtype=jnp.float32,
                   cache_dtype=jnp.float32)
    part1 = eng_a.generate(prompt, 5, greedy()).tokens
    eng_a.save_session(str(tmp_path / "s.npz"))
    want = eng_a.generate([part1[-1]], 5, greedy()).tokens

    # dense weights: the tiny spec's hidden_dim (96) cannot block-split
    # q40 cols at tp=2; the restore path under test is the CACHE placement
    eng_b = Engine(spec, load_params(spec, host, mode="dense",
                                     dtype=jnp.float32),
                   make_mesh(tp=2, dp=1),
                   compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                   use_pallas=False)
    eng_b.load_session(str(tmp_path / "s.npz"))
    got = eng_b.generate([part1[-1]], 5, greedy()).tokens
    assert got == want, (got, want)


def test_chat_session_rejects_multihost_and_pp(tmp_path):
    """--session save fetches the cache to the host, which cannot work for
    multi-process meshes or stage-stacked pp caches — chat must refuse the
    combination up front instead of crashing after the first turn."""
    from distributed_llama_tpu.apps import dllama
    from distributed_llama_tpu.testing import write_fixture

    rng = np.random.default_rng(23)
    mpath, tpath = write_fixture(tmp_path, rng=rng, seq_len=192)
    with pytest.raises(SystemExit, match="session"):
        dllama.main(["chat", "--model", mpath, "--tokenizer", tpath,
                     "--pp", "2", "--session", str(tmp_path / "s.npz")])


def test_chat_session_flag_resumes(tmp_path, capsys, monkeypatch):
    """CLI: `chat --session FILE` saves after each turn and resumes —
    the resumed process continues from the cached positions."""
    from distributed_llama_tpu.apps import dllama
    from distributed_llama_tpu.testing import write_fixture

    rng = np.random.default_rng(23)
    mpath, tpath = write_fixture(tmp_path, rng=rng, seq_len=192)
    sess = str(tmp_path / "chat.npz")

    import builtins

    inputs = iter(["", "ab"])

    def fake_input(*a):
        try:
            return next(inputs)
        except StopIteration:
            raise EOFError

    monkeypatch.setattr(builtins, "input", fake_input)
    dllama.main(["chat", "--model", mpath, "--tokenizer", tpath,
                 "--steps", "3", "--seed", "7", "--temperature", "0",
                 "--session", sess])
    capsys.readouterr()

    # resume twice from the SAME saved file (each run overwrites it on its
    # own save): once plain, once with speculation fed by the restored
    # token history — the assistant output must be identical (greedy
    # parity regardless of draft acceptance)
    import shutil

    saved = str(tmp_path / "orig.npz")
    shutil.copy(sess, saved)

    def resume(extra):
        shutil.copy(saved, sess)
        it = iter(["ba"])

        def fake(*a):
            try:
                return next(it)
            except StopIteration:
                raise EOFError

        monkeypatch.setattr(builtins, "input", fake)
        dllama.main(["chat", "--model", mpath, "--tokenizer", tpath,
                     "--steps", "3", "--seed", "7", "--temperature", "0",
                     "--session", sess] + extra)
        return capsys.readouterr().out

    out_plain = resume([])
    out_spec = resume(["--lookup-decode", "5"])
    assert "resumed session" in out_plain and "resumed session" in out_spec
    # identical transcript: compare from the assistant marker on
    tail = lambda o: o[o.index("🤖"):]
    assert tail(out_spec) == tail(out_plain)
