"""Multi-replica failover router (runtime/router.py + runtime/faults.py
replica sites).

The chaos contract under test: with 2 replicas serving a fixed trace,
killing one replica mid-trace yields ZERO client-visible failures for
queued/not-yet-streamed requests (retried on the survivor, greedy tokens
BIT-IDENTICAL to the single-engine oracle — cold or seeded prefix cache),
structured NON-retryable error frames for mid-stream ones, and the
service-level readiness (/readyz's ``router.ready``) stays True
throughout; rolling drain of each replica in turn completes the full
trace with zero failed requests. Placement is cache-aware (SGLang-style
longest-prefix) with least-loaded fallback and session affinity, and a
flapping replica is unrouted by the router's own circuit breaker until a
half-open probe succeeds.

Everything runs on CPU with count-deterministic, KEY-FILTERED fault
injection (``replica_raise``/``replica_stall`` with ``key="rK"`` only
count replica K's steps), so the kill lands on the same replica at the
same step every run. f32 engines so parity assertions compare bit-exactly
against the single-row oracle (same discipline as test_resilience.py).
"""

import threading
import time

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.models.params import load_params, random_tensors
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.runtime.faults import FAULTS, FaultError, FaultRegistry
from distributed_llama_tpu.runtime.resilience import EngineUnready
from distributed_llama_tpu.runtime.router import Router
from distributed_llama_tpu.runtime.scheduler import (PromptTooLong, QueueFull,
                                                     RequestError)
from distributed_llama_tpu.sampler import Sampler

SEQ = 64
BL = 4  # prefix-cache block length: small so short prompts publish blocks


@pytest.fixture(scope="module")
def tiny():
    spec = ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=128, seq_len=SEQ,
                     hidden_act=HiddenAct.SILU)
    host = random_tensors(spec, seed=3, scale=0.05)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    return spec, params


@pytest.fixture(autouse=True)
def clean_faults():
    FAULTS.clear()
    yield
    FAULTS.clear()


def _factory(tiny, batch=2):
    spec, params = tiny

    def make():
        return Engine(spec, params, batch=batch, compute_dtype=jnp.float32,
                      cache_dtype=jnp.float32)

    return make


def _greedy(spec):
    return Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=1)


def _oracle(spec, params, prompt, max_tokens):
    eng = Engine(spec, params, batch=1, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    return eng.generate(prompt, max_tokens, _greedy(spec)).tokens


def _router(tiny, **kw):
    kw.setdefault("replicas", 2)
    kw.setdefault("chunk", 8)
    kw.setdefault("stall_timeout", 60.0)
    kw.setdefault("backoff_base", 0.01)
    return Router(_factory(tiny), **kw)


def _wait(pred, timeout=30.0, poll=0.01):
    end = time.perf_counter() + timeout
    while time.perf_counter() < end:
        if pred():
            return True
        time.sleep(poll)
    return False


# -- the key-filtered fault sites ----------------------------------------


def test_replica_fault_key_filters_and_counts_per_replica():
    """An armed key=r0 spec neither fires NOR counts a hit for other
    callers — after=N stays deterministic per replica."""
    r = FaultRegistry()
    r.arm("replica_raise", key="r0", after=1)
    r.fire("replica_raise", key="r1")   # other replica: not even a hit
    r.fire("replica_raise", key=None)   # non-replica scheduler: ignored
    r.fire("replica_raise", key="r0")   # hit 1: skipped by after=1
    with pytest.raises(FaultError):
        r.fire("replica_raise", key="r0")  # hit 2: fires
    assert r.fired("replica_raise") == 1
    # keyless arming keeps firing for any caller (backward compatible)
    r.arm("replica_raise")
    with pytest.raises(FaultError):
        r.fire("replica_raise", key="r7")
    # env-driven arming carries the key through DLLAMA_FAULTS
    r2 = FaultRegistry()
    r2.load_env({"DLLAMA_FAULTS": "replica_raise:key=r1;times=1"})
    r2.fire("replica_raise", key="r0")
    with pytest.raises(FaultError):
        r2.fire("replica_raise", key="r1")


# -- placement: cache-aware, affinity, fallback --------------------------


def test_cache_aware_routing_prefers_warm_replica(tiny):
    """The SGLang placement rule: a prompt whose prefix one replica's
    radix tree caches routes there; cold prompts fall back least-loaded
    (lowest id on an idle tie)."""
    spec, params = tiny
    router = _router(tiny, prefix_blocks=32, prefix_block_len=BL)
    try:
        p = [1, 9, 23, 54, 7, 11, 40, 3, 15]  # two whole BL-blocks publish
        r1 = router.submit(p, 3, _greedy(spec))
        assert list(r1.tokens(timeout=60.0)) == _oracle(spec, params, p, 3)
        assert r1.replica_id == 0  # idle tie-break: lowest id
        # replica 0 published p's prefix at prefill-finish: the repeat
        # request must be placed by CACHE MATCH, not fallback
        r2 = router.submit(p, 3, _greedy(spec))
        assert list(r2.tokens(timeout=60.0)) == _oracle(spec, params, p, 3)
        assert r2.replica_id == 0
        assert router.stats.routed_cache_hit == 1
        assert router.replicas[0].match_len(p) >= BL
        assert router.replicas[1].match_len(p) == 0
    finally:
        router.close()


def test_session_affinity_sticks_and_survives_policy(tiny):
    spec, params = tiny
    router = _router(tiny, policy="round_robin")
    try:
        q = [2, 40, 77, 5]
        a = router.submit(q, 2, _greedy(spec), session="conv-1")
        list(a.tokens(timeout=60.0))
        # round_robin would alternate; affinity must override it
        b = router.submit(q, 2, _greedy(spec), session="conv-1")
        list(b.tokens(timeout=60.0))
        assert a.replica_id == b.replica_id
        assert router.stats.routed_affinity == 1
    finally:
        router.close()


# -- failover: the headline parity contracts -----------------------------


def test_failover_pre_first_token_token_parity_cold(tiny):
    """A greedy request whose first replica is KILLED before its first
    token streams must return bit-identical tokens from the surviving
    replica (cold prefix cache), with no client-visible error."""
    spec, params = tiny
    router = _router(tiny, retry_budget=1)
    try:
        p = [1, 9, 23, 54, 7]
        # kill replica 0's next WORKING step: the idle tie places p there
        FAULTS.arm("replica_raise", key="r0")
        req = router.submit(p, 6, _greedy(spec))
        got = list(req.tokens(timeout=60.0))
        assert got == _oracle(spec, params, p, 6)
        assert req.retries == 1 and req.replica_id == 1
        assert FAULTS.fired("replica_raise") == 1  # it DID die mid-trace
        assert router.stats.retries == 1
        assert router.stats.failovers_ok == 1
        # replica 0 recovers behind the scenes; the service never blinked
        assert _wait(lambda: router.replicas[0].ready, 30.0)
    finally:
        router.close()


def test_failover_token_parity_seeded_prefix_cache(tiny):
    """Same kill, but the SURVIVOR's radix tree already caches the
    prompt's prefix: the retried request seeds from blocks and must STILL
    be bit-identical (the PR-4 seeded==cold guarantee, now load-bearing
    for failover)."""
    spec, params = tiny
    router = _router(tiny, retry_budget=1, prefix_blocks=32,
                     prefix_block_len=BL)
    try:
        p = [1, 9, 23, 54, 7, 11, 40, 3, 15]
        want = _oracle(spec, params, p, 6)
        # warm BOTH replicas' trees directly (router placement would
        # cache-route the second warmup to the first's replica)
        for h in router.replicas:
            w = h.sup.submit(p, 1, _greedy(spec))
            assert list(w.tokens(timeout=60.0))
        assert all(h.match_len(p) >= BL for h in router.replicas)
        FAULTS.arm("replica_raise", key="r0")
        req = router.submit(p, 6, _greedy(spec))
        got = list(req.tokens(timeout=60.0))
        assert got == want
        assert req.retries == 1 and req.replica_id == 1
        # the retry hit the survivor's cache (seeded, not cold)
        pc = router.replicas[1].sup.prefix_cache
        assert pc.stats.hits >= 1
    finally:
        router.close()


def test_midstream_kill_emits_structured_nonretryable_frame(tiny):
    """A request killed AFTER tokens streamed is never silently replayed:
    the client gets the structured frame, retryable=False, and the
    partial-stream count is in the message."""
    spec, params = tiny
    router = _router(tiny, retry_budget=3)
    try:
        FAULTS.arm("slow_step", times=0, ms=25.0)  # pace so the kill
        # provably lands mid-stream, not after completion
        req = router.submit([1, 9, 23], 40, _greedy(spec))
        it = req.tokens(timeout=60.0)
        got = [next(it)]  # the stream is LIVE
        FAULTS.arm("replica_raise", key=f"r{req.replica_id}")
        with pytest.raises(RequestError) as ei:
            for t in it:
                got.append(t)
        assert ei.value.retryable is False
        assert ei.value.code == "engine_error"
        assert "already streamed" in str(ei.value)
        assert req.finish_reason == "error"
        assert router.stats.midstream_failures == 1
        assert router.stats.retries == 0  # no silent replay happened
    finally:
        router.close()


# -- the acceptance chaos trace ------------------------------------------


def test_kill_one_replica_mid_trace_zero_unstreamed_failures(tiny):
    """ISSUE 6 acceptance: a fixed Poisson trace over 2 replicas with
    replica 0 killed mid-trace — every request either completes (retried
    ones greedy-parity-checked against the oracle) or, ONLY if it already
    streamed tokens, fails with the structured non-retryable frame; the
    router stays ready the whole time (single-replica failure is
    invisible at the service level)."""
    spec, params = tiny
    router = _router(tiny, retry_budget=1, circuit_threshold=100)
    n_req, budget = 10, 6
    rng = np.random.default_rng(0)
    prompts = [[int(t) for t in rng.integers(1, spec.vocab_size, 5)]
               for _ in range(n_req)]
    arrivals = np.cumsum(rng.exponential(0.05, n_req))
    oracles = {i: _oracle(spec, params, p, budget)
               for i, p in enumerate(prompts)}
    results: dict = {}
    ready_gaps = []
    sampling = threading.Event()
    sampling.set()

    def sample_ready():
        while sampling.is_set():
            if not router.ready:
                ready_gaps.append(time.perf_counter())
            time.sleep(0.005)

    def client(i):
        req = router.submit(prompts[i], budget, _greedy(spec))
        got = []
        try:
            for t in req.tokens(timeout=120.0):
                got.append(t)
            results[i] = ("ok", got, req.retries)
        except RequestError as e:
            results[i] = ("error", got, e)

    try:
        FAULTS.arm("slow_step", times=0, ms=20.0)  # pace: the trace must
        # still be in flight when the kill lands
        FAULTS.arm("replica_raise", key="r0", after=4)  # deterministic
        # kill on replica 0's 5th working step, mid-trace
        samp = threading.Thread(target=sample_ready, daemon=True)
        samp.start()
        threads = []
        t0 = time.perf_counter()
        for i in range(n_req):
            dt = t0 + arrivals[i] - time.perf_counter()
            if dt > 0:
                time.sleep(dt)
            th = threading.Thread(target=client, args=(i,), daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=120.0)
            assert not th.is_alive(), "a client hung"
    finally:
        sampling.clear()
        FAULTS.clear()
    assert FAULTS.fired("replica_raise") == 0  # cleared; it fired earlier
    assert len(results) == n_req
    errored = [i for i, r in results.items() if r[0] == "error"]
    for i, r in results.items():
        if r[0] == "ok":
            assert r[1] == oracles[i], f"request {i} lost greedy parity"
        else:
            # ONLY mid-stream requests may fail, and only structurally
            kind, got, exc = r
            assert len(got) >= 1, \
                f"request {i} failed with NO tokens streamed: {exc}"
            assert exc.retryable is False
    # the kill really happened and failover really ran
    assert router.replicas[0].sup.sup_stats.crashes >= 1
    assert router.stats.retries >= 1 or errored
    # service-level readiness never blinked
    assert not ready_gaps, f"router went unready at {ready_gaps}"
    router.close()


def test_rolling_drain_completes_trace_with_zero_failures(tiny):
    """ISSUE 6 acceptance: rolling drain+restart of each replica in turn
    while a trace is in flight — zero failed requests, service ready
    throughout, both replicas rebuilt."""
    spec, params = tiny
    router = _router(tiny)
    n_req, budget = 8, 4
    rng = np.random.default_rng(1)
    prompts = [[int(t) for t in rng.integers(1, spec.vocab_size, 5)]
               for _ in range(n_req)]
    results: dict = {}

    def client(i):
        try:
            req = router.submit(prompts[i], budget, _greedy(spec))
            results[i] = ("ok", list(req.tokens(timeout=120.0)))
        except Exception as e:  # noqa: BLE001 — any failure fails the bar
            results[i] = ("error", e)

    try:
        FAULTS.arm("slow_step", times=0, ms=15.0)  # keep work in flight
        threads = []
        for i in range(n_req):
            th = threading.Thread(target=client, args=(i,), daemon=True)
            th.start()
            threads.append(th)
            time.sleep(0.04)
            if i == 2:
                # roll both replicas mid-trace, one at a time
                roller = threading.Thread(
                    target=lambda: router.rolling_restart(timeout=60.0),
                    daemon=True)
                roller.start()
        for th in threads:
            th.join(timeout=120.0)
            assert not th.is_alive()
        roller.join(timeout=120.0)
        assert not roller.is_alive()
    finally:
        FAULTS.clear()
    assert len(results) == n_req
    bad = {i: r for i, r in results.items() if r[0] != "ok"}
    assert not bad, f"rolling drain failed requests: {bad}"
    for i, (_, got) in results.items():
        assert got == _oracle(spec, params, prompts[i], budget), i
    assert router.stats.drains == 2 and router.stats.restarts == 2
    assert router.ready
    router.close()


# -- router circuit breaker ----------------------------------------------


def test_router_circuit_opens_and_half_open_probe_closes(tiny):
    """A flapping replica (crashes every request but keeps recovering to
    'ready') is unrouted after circuit_threshold consecutive failures;
    after the cooldown exactly one half-open probe goes through, and its
    success closes the circuit."""
    spec, params = tiny
    router = _router(tiny, retry_budget=1, circuit_threshold=2,
                     circuit_cooldown=5.0, breaker_threshold=1000)
    try:
        p = [1, 9, 23, 54]
        want = _oracle(spec, params, p, 3)  # built ONCE: engine
        # construction inside the loop would eat the cooldown window
        FAULTS.arm("replica_raise", key="r0", times=0)  # r0 flaps forever
        for _ in range(2):  # two failovers attribute two failures to r0
            assert _wait(lambda: router.replicas[0].ready, 30.0)
            req = router.submit(p, 3, _greedy(spec))
            assert list(req.tokens(timeout=60.0)) == want
            assert req.retries == 1
        assert router.stats.breaker_trips == 1
        h0 = router.replicas[0]
        assert h0.open_until > time.perf_counter()
        # circuit open: traffic skips r0 even though its supervisor says
        # ready — no retry needed, no crash burned
        assert _wait(lambda: h0.ready, 30.0)
        crashes_before = h0.sup.sup_stats.crashes
        req = router.submit(p, 3, _greedy(spec))
        assert list(req.tokens(timeout=60.0)) == want
        assert req.replica_id == 1 and req.retries == 0
        assert h0.sup.sup_stats.crashes == crashes_before
        # fault gone + cooldown elapsed: the half-open probe lands on r0,
        # succeeds, and closes the circuit
        FAULTS.clear()
        assert _wait(lambda: time.perf_counter() >= h0.open_until, 10.0)
        req = router.submit(p, 3, _greedy(spec))
        assert list(req.tokens(timeout=60.0)) == want
        assert req.replica_id == 0
        assert router.stats.breaker_probes == 1
        assert h0.open_until == 0.0 and h0.fails == 0
    finally:
        router.close()


def test_half_open_probe_door_refusal_returns_to_half_open(tiny):
    """A half-open probe refused at the replica's DOOR (QueueFull /
    EngineUnready before any request was placed) must not leak
    probing=True — the circuit returns to half-open so a later pick can
    probe again. Regression: the leak unrouted a healthy replica forever
    (no terminal result ever ran _on_result), surviving until a manual
    reset_breaker."""
    spec, params = tiny
    router = _router(tiny, circuit_threshold=1, circuit_cooldown=0.05)
    try:
        h0 = router.replicas[0]
        assert _wait(lambda: h0.ready and router.replicas[1].ready, 30.0)
        router._on_result(h0, ok=False)  # threshold 1: circuit opens
        assert h0.open_until > 0.0
        time.sleep(0.06)                 # past cooldown: next pick probes
        real_submit = h0.sup.submit

        def refuse_once(*a, **k):
            h0.sup.submit = real_submit
            raise QueueFull(1, 1)

        h0.sup.submit = refuse_once
        req = router.submit([1, 9], 2, _greedy(spec))  # probe refused -> r1
        assert req.replica_id == 1
        assert list(req.tokens(timeout=60.0)) == _oracle(
            spec, params, [1, 9], 2)
        assert not h0.probing            # the leak: this used to stay True
        # ...so the NEXT cold pick lands the probe on r0 and closes it
        time.sleep(0.06)
        req = router.submit([2, 7], 2, _greedy(spec))
        assert req.replica_id == 0
        assert list(req.tokens(timeout=60.0)) == _oracle(
            spec, params, [2, 7], 2)
        assert h0.open_until == 0.0 and not h0.probing
        assert router.stats.breaker_probes == 2
    finally:
        router.close()


def test_probe_survives_caller_error_prompt_too_long(tiny):
    """A CALLER error raised by the replica's door (PromptTooLong — an
    HTTP-reachable 400) while that replica is half-open must propagate
    to the client yet release the armed probe. Regression: the leak left
    probing=True forever, so one oversized request permanently unrouted
    a healthy replica."""
    spec, params = tiny
    router = _router(tiny, circuit_threshold=1, circuit_cooldown=0.05)
    try:
        h0 = router.replicas[0]
        assert _wait(lambda: h0.ready and router.replicas[1].ready, 30.0)
        router._on_result(h0, ok=False)   # threshold 1: circuit opens
        time.sleep(0.06)                  # half-open: next pick probes r0
        with pytest.raises(PromptTooLong):
            router.submit(list(range(1, SEQ + 2)), 2, _greedy(spec))
        assert not h0.probing             # the 400 did not eat the probe
        # ...so a well-formed request can still probe r0 and close it
        req = router.submit([5, 6], 2, _greedy(spec))
        assert req.replica_id == 0
        assert list(req.tokens(timeout=60.0)) == _oracle(
            spec, params, [5, 6], 2)
        assert h0.open_until == 0.0 and h0.fails == 0
    finally:
        router.close()


def test_abandoned_stream_settles_probe_and_circuit(tiny):
    """A consumer that stops iterating mid-stream (text-level stop
    sequence, chat end-marker, client disconnect) never reaches a
    terminal event — generator teardown must still settle the router
    circuit. Regression: a streamed-then-abandoned half-open probe
    leaked probing=True (permanently unrouting the replica) and its
    success never reset h.fails."""
    spec, params = tiny
    router = _router(tiny, circuit_threshold=1, circuit_cooldown=0.05)
    try:
        h0 = router.replicas[0]
        assert _wait(lambda: h0.ready and router.replicas[1].ready, 30.0)
        router._on_result(h0, ok=False)   # threshold 1: circuit opens
        assert h0.open_until > 0.0
        time.sleep(0.06)                  # half-open: next pick probes
        req = router.submit([3, 11], 4, _greedy(spec))
        assert req.replica_id == 0 and h0.probing
        gen = req.tokens(timeout=60.0)
        next(gen)                         # one token streamed, then the
        gen.close()                       # consumer walks away
        req.cancel()
        assert not h0.probing             # teardown settled the probe...
        assert h0.open_until == 0.0 and h0.fails == 0  # ...as a success
        assert req.finished.is_set()
        assert router.stats.breaker_probes == 1
    finally:
        router.close()


def test_cancel_without_consuming_releases_probe(tiny):
    """submit() arms the probe, but the caller cancels before ever
    iterating tokens() (client gone pre-stream): neither a terminal
    verdict nor generator teardown will run, so cancel() itself must
    release the probe. Regression: the leak left probing=True forever."""
    spec, params = tiny
    router = _router(tiny, circuit_threshold=1, circuit_cooldown=0.05)
    try:
        h0 = router.replicas[0]
        assert _wait(lambda: h0.ready and router.replicas[1].ready, 30.0)
        router._on_result(h0, ok=False)   # threshold 1: circuit opens
        time.sleep(0.06)                  # half-open: next pick probes r0
        req = router.submit([7, 13], 3, _greedy(spec))
        assert req.replica_id == 0 and req._probe
        req.cancel()                      # never iterates tokens()
        assert not h0.probing
        # the replica can still be probed (and closed) by a later request
        assert _wait(lambda: not any(
            s.req is not None for s in h0.sup._sched.slots), 30.0)
        time.sleep(0.06)
        req = router.submit([8, 14], 2, _greedy(spec))
        assert req.replica_id == 0
        assert list(req.tokens(timeout=60.0)) == _oracle(
            spec, params, [8, 14], 2)
        assert h0.open_until == 0.0
    finally:
        router.close()


def test_no_routable_replica_is_structured_rejection(tiny):
    """Every replica drained -> submit is a fast EngineUnready (the 503 +
    Retry-After shape), counted; undrain restores service without a
    rebuild (router-level drain keeps the supervisor READY)."""
    spec, params = tiny
    router = _router(tiny)
    try:
        for h in router.replicas:
            assert h.drain(timeout=30.0)
        with pytest.raises(EngineUnready) as ei:
            router.submit([1, 9], 2, _greedy(spec))
        assert ei.value.retry_after > 0
        assert router.stats.no_replica_rejections == 1
        assert not router.ready
        router.undrain_replica(0)
        assert router.ready
        req = router.submit([1, 9], 2, _greedy(spec))
        assert list(req.tokens(timeout=60.0)) == _oracle(
            spec, params, [1, 9], 2)
        assert req.replica_id == 0
    finally:
        router.close()


def test_stats_totals_carry_across_replica_restart(tiny):
    """/stats aggregation while a replica restarts: counter totals must
    neither reset nor double-count across the rebuild (the handle folds
    the dead supervisor's lifetime totals into a carry — the same
    contract SupervisorStats keeps across engine rebuilds, and the same
    bar the process tier pins across a SIGKILL respawn in
    tests/test_replica_procs.py)."""
    spec, params = tiny
    router = _router(tiny)
    try:
        p = [1, 9, 23]
        for _ in range(3):
            req = router.submit(p, 2, _greedy(spec))
            assert list(req.tokens(timeout=60.0)) == _oracle(
                spec, params, p, 2)
        s1 = router.summary()
        assert s1["requests_finished"] == 3 and s1["tokens_out"] == 6
        # restart replica 0 (it served at least one of the three —
        # cache-aware placement routed the repeats to it)
        assert router.drain_replica(0, timeout=30.0)
        router.restart_replica(0, timeout=60.0)
        s2 = router.summary()
        assert s2["requests_finished"] == 3      # carried, not reset
        assert s2["tokens_out"] == 6             # and not double-counted
        r0 = next(r for r in s2["replicas"] if r["replica"] == 0)
        assert r0["state"] == "ready" and not r0["draining"]
        req = router.submit(p, 2, _greedy(spec))
        assert list(req.tokens(timeout=60.0)) == _oracle(
            spec, params, p, 2)
        s3 = router.summary()
        assert s3["requests_finished"] == 4 and s3["tokens_out"] == 8
    finally:
        router.close()


def test_router_summary_aggregates_and_reports_replicas(tiny):
    spec, params = tiny
    router = _router(tiny)
    try:
        for _ in range(2):
            req = router.submit([1, 9, 23], 2, _greedy(spec))
            list(req.tokens(timeout=60.0))
        s = router.summary()
        assert s["state"] == "ready"
        assert s["requests_finished"] == 2
        assert s["tokens_out"] == 4
        assert s["ttft_p50_ms"] is not None
        assert len(s["replicas"]) == 2
        assert {r["replica"] for r in s["replicas"]} == {0, 1}
        assert s["router"]["routed"] == 2
        assert s["router"]["policy"] == "cache_aware"
        # per-replica summaries carry their own resilience blocks
        assert all("resilience" in r for r in s["replicas"])
    finally:
        router.close()
