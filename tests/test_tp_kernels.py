"""shard_map Pallas-kernel execution on multi-device meshes.

GSPMD cannot auto-partition a pallas_call, so tp/dp meshes run the fused Q40
matmul and flash decode attention per-shard inside shard_map
(parallel/tp_q80.py). These tests run the kernels in interpret mode on the
virtual 8-device CPU mesh and require the full engine (prefill + decode) to
reproduce the single-device greedy token stream — the integration-level
equivalent of the reference's slice-equivalence checks
(ref: src/transformer-test.cpp:21-72).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llama_tpu.models import ArchType
from distributed_llama_tpu.models.params import load_params
from distributed_llama_tpu.parallel import make_mesh
from distributed_llama_tpu.parallel.tp_q80 import TpColWeight, TpRowWeight
from distributed_llama_tpu.runtime import Engine
from distributed_llama_tpu.sampler import Sampler

from test_model_forward import make_spec, dense_weights

PROMPT = [1, 7, 3, 9]


def greedy():
    return Sampler(256, temperature=0.0, topp=0.9, seed=1)


def q40_params(arch=ArchType.LLAMA, seed=5):
    spec = make_spec(arch, dim=128, n_heads=8, n_kv_heads=4, hidden_dim=256)
    host, _ = dense_weights(spec, seed=seed)
    return spec, load_params(spec, host, mode="q40", dtype=jnp.float32)


def baseline_tokens(spec, params, prompt=PROMPT, n=8):
    eng = Engine(spec, params, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32, use_pallas=False)
    return eng.generate(prompt, max_tokens=n, sampler=greedy()).tokens


@pytest.mark.parametrize("arch", [ArchType.LLAMA, ArchType.MIXTRAL])
def test_tp_pallas_decode_matches_single_device(arch):
    spec, params = q40_params(arch)
    want = baseline_tokens(spec, params)
    eng = Engine(spec, params, make_mesh(tp=4, dp=1),
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                 use_pallas=True, pallas_interpret=True)
    assert eng.use_pallas and eng._tp_mesh is not None
    got = eng.generate(PROMPT, max_tokens=8, sampler=greedy()).tokens
    assert got == want, (got, want)


def test_tp_pallas_weights_are_marked():
    """Q40 weights must be wrapped (row markers / col stacks) so every matmul
    actually takes the shard_map kernel path, not the GSPMD dequant path."""
    spec, params = q40_params()
    eng = Engine(spec, params, make_mesh(tp=4, dp=1),
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                 use_pallas=True, pallas_interpret=True)
    lw = eng.params["layers"][0]
    assert isinstance(lw["wq"], TpRowWeight)
    assert isinstance(lw["w1"], TpRowWeight)
    assert isinstance(lw["wo"], TpColWeight)
    assert isinstance(lw["w2"], TpColWeight)
    assert isinstance(eng.params["wcls"], TpRowWeight)
    # row shards place output rows on tp — entering shard_map moves no bytes
    assert eng.params["layers"][0]["wq"].w.packed.sharding.spec[0] == "tp"


def test_dp_tp_pallas_batched_generation():
    spec, params = q40_params()
    want_a = baseline_tokens(spec, params, PROMPT, n=6)
    want_b = baseline_tokens(spec, params, PROMPT[:2], n=6)
    eng = Engine(spec, params, make_mesh(tp=2, dp=2), batch=2,
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                 use_pallas=True, pallas_interpret=True)
    outs = eng.generate_batch([PROMPT, PROMPT[:2]], max_tokens=6,
                              sampler=greedy())
    assert outs == [want_a, want_b], (outs, [want_a, want_b])


def test_dp_only_mesh_pallas():
    """dp-only mesh: weights replicated, batch sharded; the row marker still
    routes matmuls through shard_map so the Pallas kernel sees local
    operands."""
    spec, params = q40_params()
    want = baseline_tokens(spec, params, PROMPT, n=5)
    eng = Engine(spec, params, make_mesh(tp=1, dp=2), batch=2,
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                 use_pallas=True, pallas_interpret=True)
    outs = eng.generate_batch([PROMPT, PROMPT], max_tokens=5,
                              sampler=greedy())
    assert outs == [want, want], (outs, want)


def test_tp_pallas_q80_collectives_close():
    """Pallas kernels + the quantized partial-sum exchange compose; results
    stay within block-quantization error of the exact path (tokens may
    diverge late with random weights, so compare one step's logits)."""
    spec, params = q40_params()
    mesh = make_mesh(tp=4, dp=1)
    exact = Engine(spec, params, mesh, compute_dtype=jnp.float32,
                   cache_dtype=jnp.float32, use_pallas=True,
                   pallas_interpret=True)
    q80 = Engine(spec, params, mesh, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32, use_pallas=True,
                 pallas_interpret=True, activation_q80=True,
                 q80_collectives=True)
    assert q80.tp_reduce == "q80" and exact.tp_reduce == "exact"
    tok = np.asarray([PROMPT], np.int32)
    le = np.asarray(exact.step(tok, 0))
    lq = np.asarray(q80.step(tok, 0))
    assert np.isfinite(lq).all()
    np.testing.assert_allclose(lq, le, atol=0.05, rtol=0)
