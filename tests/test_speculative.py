"""Prompt-lookup speculative decoding (runtime/speculative.py +
Engine.generate_lookup).

The invariant everything hangs on: the emitted stream is EXACTLY the plain
greedy stream — drafts only decide how many positions one forward confirms.
The reference has no speculation at all (one token per forward,
ref: src/apps/dllama/dllama.cpp:43-81).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llama_tpu.models import ArchType
from distributed_llama_tpu.models.params import load_params
from distributed_llama_tpu.runtime import Engine
from distributed_llama_tpu.runtime.speculative import count_accepted, find_draft
from distributed_llama_tpu.sampler import Sampler

from test_model_forward import make_spec, dense_weights


def test_find_draft_prefers_longest_ngram():
    h = np.asarray([5, 6, 7, 9, 5, 6, 7], np.int32)
    # trailing 3-gram (5,6,7) occurred at 0; continuation starts with 9
    assert find_draft(h, 4) == [9, 5, 6, 7]
    assert find_draft(h, 1) == [9]
    # no match at all
    assert find_draft(np.asarray([1, 2, 3, 4], np.int32), 4) == []
    # 1-gram fallback: trailing 4 occurred at index 0, continuation [8, 2]
    assert find_draft(np.asarray([4, 8, 2, 4], np.int32), 2) == [8, 2]
    # last occurrence wins when a pattern repeats
    h2 = np.asarray([3, 1, 7, 3, 1, 8, 3, 1], np.int32)
    assert find_draft(h2, 1, max_ngram=2) == [8]


def test_find_draft_full_continuation_preference():
    # trailing (1,2) occurs at 0 (full 3-token continuation) and at 5
    # (only 2 tokens left): the older, full match must win at draft_len=3
    h = np.asarray([1, 2, 9, 8, 7, 1, 2, 1, 2], np.int32)
    assert find_draft(h, 3, max_ngram=2) == [9, 8, 7]
    # when the recent match satisfies the budget, recency wins
    assert find_draft(h, 2, max_ngram=2) == [1, 2]


def test_find_draft_property_fuzz():
    """For random histories, assert the draft's properties WITHOUT
    re-implementing the selection rule: the draft must be the exact
    continuation of SOME earlier occurrence of the winning (longest
    matching) trailing n-gram, and whenever any earlier occurrence has a
    full draft_len continuation available, the draft must be full
    length (the anti-truncation guarantee)."""
    rng = np.random.default_rng(3)
    for _ in range(200):
        n = int(rng.integers(2, 40))
        h = rng.integers(0, 6, n).astype(np.int32)  # small alphabet: matches
        d = find_draft(h, 4, max_ngram=3)
        if not d:
            # no trailing 1..3-gram may occur earlier
            for k in (3, 2, 1):
                if n < k + 1:
                    continue
                pat = h[-k:]
                win = np.lib.stride_tricks.sliding_window_view(h, k)
                hits = np.nonzero((win == pat).all(axis=1))[0]
                assert not (hits < n - k).any(), (h, k)
            continue
        ok = False
        for k in (3, 2, 1):  # longest match wins
            if n < k + 1:
                continue
            pat = h[-k:]
            win = np.lib.stride_tricks.sliding_window_view(h, k)
            hits = np.nonzero((win == pat).all(axis=1))[0]
            hits = hits[hits < n - k]
            if hits.size:
                # the draft is SOME hit's exact continuation...
                assert any(d == h[j + k: j + k + 4].tolist()
                           for j in hits), (h, k, d)
                # ...and is full-length whenever any hit could supply one
                if (hits + k + 4 <= n).any():
                    assert len(d) == 4, (h, k, d)
                ok = True
                break
        assert ok, (h, d)


def test_count_accepted():
    assert count_accepted([4, 5, 6], np.asarray([4, 5, 9, 0])) == 2
    assert count_accepted([4], np.asarray([7, 1])) == 0
    assert count_accepted([], np.asarray([7])) == 0


def _engine(spec, host):
    params = load_params(spec, host, mode="q40", dtype=jnp.float32)
    return Engine(spec, params, compute_dtype=jnp.float32,
                  cache_dtype=jnp.float32)


@pytest.mark.parametrize("draft_len", [1, 4, 7])
def test_lookup_matches_plain_greedy(draft_len):
    """Exact greedy parity across draft lengths — accepted and rejected
    drafts must never change the emitted tokens (greedy output of a tiny
    random model is near-random, so rejection paths get exercised)."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=96)
    host, _ = dense_weights(spec, seed=41)
    prompt = [1, 5, 9, 1, 5]  # repeated bigram seeds the n-gram table

    want = _engine(spec, host).generate(
        prompt, 24, Sampler(spec.vocab_size, 0.0, 0.9, 1, backend="python"),
    ).tokens

    eng = _engine(spec, host)
    got = eng.generate_lookup(prompt, 24, draft_len=draft_len)
    assert got.tokens == want, (draft_len, got.tokens, want)
    fwd, n = eng.last_accept_stats
    assert n == len(want) and fwd <= n + 1


def test_lookup_accepts_on_repetitive_continuation():
    """A model whose greedy continuation loops must confirm multiple tokens
    per forward (tokens/forward > 1) — the point of the feature."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=160)
    host, _ = dense_weights(spec, seed=43)
    eng0 = _engine(spec, host)
    probe = eng0.generate(
        [2, 7], 96, Sampler(spec.vocab_size, 0.0, 0.9, 1, backend="python"),
    ).tokens
    # tiny random models nearly always enter a cycle within ~100 tokens;
    # skip (not fail) on the rare seed that stays aperiodic
    tail = probe[-24:]
    if len(set(tail)) > len(tail) - 4:
        pytest.skip("greedy stream did not become repetitive for this seed")

    eng = _engine(spec, host)
    out = eng.generate_lookup([2, 7], 96, draft_len=7)
    assert out.tokens == probe
    fwd, n = eng.last_accept_stats
    assert n / fwd > 1.5, (fwd, n)


def test_lookup_respects_tokenizer_vocab_truncation():
    """A model head padded beyond the tokenizer vocab: the lookup stream
    must argmax over the TOKENIZER's vocab like the host Sampler, or the
    streams diverge on padding-region argmaxes."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=64)
    host, _ = dense_weights(spec, seed=47)
    prompt = [1, 5, 9, 1, 5]
    tok_vocab = 96  # tokenizer smaller than the model head

    want = _engine(spec, host).generate(
        prompt, 12, Sampler(tok_vocab, 0.0, 0.9, 1, backend="python")).tokens
    got = _engine(spec, host).generate_lookup(
        prompt, 12, draft_len=4, vocab_size=tok_vocab)
    assert got.tokens == want, (got.tokens, want)
    assert all(t < tok_vocab for t in got.tokens)


def test_lookup_matches_greedy_on_kernel_path():
    """The verify forwards (t = 1 + k) route through the fused kernels on
    TPU; the interpret-mode kernel path must produce the same stream."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=96)
    host, _ = dense_weights(spec, seed=41)
    prompt = [1, 5, 9, 1, 5]
    want = _engine(spec, host).generate_lookup(prompt, 12, draft_len=4).tokens

    params = load_params(spec, host, mode="q40", dtype=jnp.float32)
    eng = Engine(spec, params, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32, use_pallas=True,
                 pallas_interpret=True)
    got = eng.generate_lookup(prompt, 12, draft_len=4)
    assert got.tokens == want, (got.tokens, want)


def test_lookup_budget_zero_emits_nothing():
    """max_tokens == 0 must emit nothing (prefill still advances the cache)
    — the plain loop's behavior at the context boundary."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=64)
    host, _ = dense_weights(spec, seed=41)
    eng = _engine(spec, host)
    out = eng.generate_lookup([1, 5, 9], 0)
    assert out.tokens == []
    assert eng.pos == 3 and eng.last_accept_stats == (1, 0)


def test_lookup_eos_truncates_and_continues():
    """A stop token inside a confirmed draft truncates the output there,
    and pos rewinds so a later generate() continues correctly."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=96)
    host, _ = dense_weights(spec, seed=41)
    prompt = [1, 5, 9, 1, 5]
    probe = _engine(spec, host).generate_lookup(prompt, 16).tokens
    eos = probe[5]

    eng = _engine(spec, host)
    out = eng.generate_lookup(prompt, 16, eos_id=eos)
    want_cut = probe[: probe.index(eos) + 1]
    assert out.tokens == want_cut
    # host-parity pos: the last emitted token is never stepped
    assert eng.pos == len(prompt) + len(want_cut) - 1

    # continuation from the rewound position matches an unbroken greedy run
    greedy = Sampler(spec.vocab_size, 0.0, 0.9, 1, backend="python")
    cont = eng.generate([out.tokens[-1]], 4, greedy).tokens
    full = _engine(spec, host).generate(prompt + want_cut, 4, greedy).tokens
    assert cont == full, (cont, full)


def test_api_lookup_decode_matches_plain(tmp_path):
    """API server: greedy requests with lookup_decode speculate (fewer
    forwards) with byte-identical responses; sampled requests speculate
    via rejection resampling (distribution-exact, seed-deterministic)."""
    from distributed_llama_tpu.apps import dllama
    from distributed_llama_tpu.apps.api_server import (
        ApiState, _completion_chunks)
    from distributed_llama_tpu.testing import write_fixture

    rng = np.random.default_rng(19)
    mpath, tpath = write_fixture(tmp_path, rng=rng, seq_len=192)

    def build_state(lookup):
        args = dllama.build_argparser().parse_args([
            "api", "--model", mpath, "--tokenizer", tpath,
            "--steps", "8", "--temperature", "0", "--seed", "3"])
        engine, tokenizer, sampler = dllama.build_engine(args)
        return ApiState(engine, tokenizer, sampler, lookup_decode=lookup)

    body = {"messages": [{"role": "user", "content": "abab"}],
            "max_tokens": 8, "temperature": 0}
    want = list(_completion_chunks(build_state(0), body))
    st = build_state(5)
    got = list(_completion_chunks(st, body))
    assert got == want
    fwd, n = st.engine.last_accept_stats
    assert n >= fwd  # speculation engaged (>= 1 token per forward)

    # sampled request: takes the rejection-resampling lookup path — the
    # token stream is a DERIVED numpy RNG's, not the plain path's xorshift
    # stream (coin parity is impossible by construction), so the contract
    # is seed-determinism, not byte parity with the plain path. The
    # distribution-exactness of the mode itself is pinned by
    # test_lookup_sampled_marginals_match_plain_sampling.
    body_s = {"messages": [{"role": "user", "content": "abab"}],
              "max_tokens": 6, "temperature": 0.8, "seed": 11}
    st_a, st_b = build_state(5), build_state(5)
    before = st_a.sampler.rng_state
    got_a = list(_completion_chunks(st_a, body_s))
    got_b = list(_completion_chunks(st_b, body_s))
    assert got_a == got_b  # identical server state + seed -> identical text
    fwd_s, n_s = st_a.engine.last_accept_stats
    assert n_s >= fwd_s  # the sampled stream really speculated
    # ... and the per-request seed restore still holds: with an explicit
    # request seed, the shared sampler stream must come back exactly where
    # it was (next_seed's advance happened on the request-seeded state and
    # is rolled back with it)
    assert st_a.sampler.rng_state == before


def test_chat_lookup_decode_matches_plain(tmp_path, capsys, monkeypatch):
    """Greedy chat turns with --lookup-decode produce the same transcript
    as the plain chat loop."""
    import builtins

    from distributed_llama_tpu.apps import dllama
    from distributed_llama_tpu.testing import write_fixture

    rng = np.random.default_rng(29)
    mpath, tpath = write_fixture(tmp_path, rng=rng, seq_len=192)

    def run(extra):
        inputs = iter(["", "abab"])

        def fake_input(*a):
            try:
                return next(inputs)
            except StopIteration:
                raise EOFError

        monkeypatch.setattr(builtins, "input", fake_input)
        dllama.main(["chat", "--model", mpath, "--tokenizer", tpath,
                     "--steps", "6", "--seed", "7", "--temperature", "0"]
                    + extra)
        return capsys.readouterr().out.splitlines()[-2:]

    want = run([])
    got = run(["--lookup-decode", "5"])
    assert got == want, (got, want)


def test_cli_lookup_decode_matches_plain(tmp_path, capsys):
    from distributed_llama_tpu.apps import dllama
    from distributed_llama_tpu.testing import write_fixture

    rng = np.random.default_rng(17)
    mpath, tpath = write_fixture(tmp_path, rng=rng, seq_len=192)
    base = ["generate", "--model", mpath, "--tokenizer", tpath,
            "--prompt", "abab", "--steps", "8", "--seed", "7",
            "--temperature", "0"]
    dllama.main(base)
    want = capsys.readouterr().out.splitlines()[-1]
    dllama.main(base + ["--lookup-decode", "5"])
    got = capsys.readouterr().out.splitlines()[-1]
    assert got == want
    # temperature > 0 + lookup now dispatches to the sampled (rejection
    # resampling) mode instead of erroring; it must run to completion
    dllama.main(["inference"] + base[1:-1] + ["0.8", "--lookup-decode", "5"])
    out_s = capsys.readouterr().out
    assert "tokens/forward" in out_s


# -- sampled speculation (rejection resampling) --------------------------


def test_accept_or_resample_marginal_is_exact():
    """The core exactness claim, tested statistically: marginalizing the
    accept/resample step over its two uniforms must reproduce p exactly,
    for drafts the model loves, hates, and everything between."""
    from distributed_llama_tpu.runtime.speculative import accept_or_resample

    rng = np.random.default_rng(11)
    p = np.asarray([0.5, 0.3, 0.15, 0.05])
    for d in range(4):  # draft = each token incl. the near-zero-mass one
        counts = np.zeros(4)
        n = 40_000
        for _ in range(n):
            _, t = accept_or_resample(p, d, rng.random(), rng.random())
            counts[t] += 1
        np.testing.assert_allclose(counts / n, p, atol=0.012,
                                   err_msg=f"draft={d}")
    # point mass: rejection impossible
    assert accept_or_resample(np.asarray([0.0, 1.0]), 1, 0.999, 0.5) == (True, 1)


def test_target_dist_matches_host_sampler():
    """target_dist must be the exact distribution Sampler.sample draws
    from: zero outside the nucleus, normalized, and statistically
    indistinguishable from 50k Sampler draws on the same logits."""
    from distributed_llama_tpu.runtime.speculative import target_dist

    rng = np.random.default_rng(5)
    logits = rng.standard_normal(64).astype(np.float32) * 2.0
    p = target_dist(logits, 0.8, 0.9, 64)
    assert abs(p.sum() - 1.0) < 1e-9
    smp = Sampler(64, 0.8, 0.9, seed=123, backend="python")
    counts = np.zeros(64)
    n = 50_000
    for _ in range(n):
        counts[smp.sample(logits)] += 1
    np.testing.assert_allclose(counts / n, p, atol=0.01)
    # every sampled token lies inside target_dist's support
    assert set(np.nonzero(counts)[0]) <= set(np.nonzero(p)[0])


def test_lookup_sampled_marginals_match_plain_sampling():
    """End-to-end: across many seeds, the sampled-lookup stream's per-
    position marginals must match plain generate()+Sampler's (the two use
    different RNGs, so only distributions can agree — that is the
    contract). The repeated-bigram prompt makes find_draft propose real
    drafts, exercising accept AND reject paths."""
    from distributed_llama_tpu.models.params import random_tensors
    from distributed_llama_tpu.runtime.speculative import target_dist

    # history primed with the model's own greedy continuation makes the
    # drafts adversarially good — the marginals must STILL match (drafts
    # may only change how many tokens a forward confirms, never what
    # distribution they come from). Verified against the EXACT marginals:
    # position 0 is target_dist(prefill logits); position 1 is
    # sum_t p0(t) * p1(.|t) enumerated over position 0's nucleus. The
    # plain host-sampler path runs as a noise-floor control.
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=64)
    host = random_tensors(spec, seed=43, scale=0.5)
    prompt = [1, 5, 9, 1, 5]
    n_runs, n_tok, v = 400, 4, spec.vocab_size

    eng = _engine(spec, host)
    lg0 = eng.fetch_logits(eng.prefill(prompt))[0]
    exact0 = target_dist(lg0, 0.8, 0.9, v)
    exact1 = np.zeros(v)
    for t1 in np.nonzero(exact0)[0]:
        eng.reset()
        eng.prefill(prompt)
        lg1 = eng.fetch_logits(
            eng.step(np.asarray([[t1]], np.int32), eng.pos))[0]
        exact1 += exact0[t1] * target_dist(lg1, 0.8, 0.9, v)

    eng.reset()
    probe = eng.generate(prompt, 24, Sampler(v, 0.0, 0.9, 1,
                                             backend="python")).tokens
    plain = np.zeros((2, v))
    for s in range(n_runs):
        eng.reset()
        toks = eng.generate(prompt, n_tok, Sampler(
            v, 0.8, 0.9, seed=1000 + s, backend="python")).tokens
        for i in (0, 1):
            plain[i, toks[i]] += 1

    spec_counts = np.zeros((2, v))
    accepted_any = rejected_any = False
    for s in range(n_runs):
        eng.reset()
        res = eng.generate_lookup_sampled(
            prompt, n_tok, temperature=0.8, topp=0.9, seed=5000 + s,
            draft_len=3, history=prompt + probe)
        fwd, n = eng.last_accept_stats
        accepted_any |= n > fwd
        # full acceptance finishes the 4-token budget in prefill + one
        # verify forward (fwd == 2); a third forward implies a reject
        rejected_any |= fwd >= 3
        for i in (0, 1):
            spec_counts[i, res.tokens[i]] += 1

    assert accepted_any and rejected_any  # both paths ran in the ensemble
    for i, exact in ((0, exact0), (1, exact1)):
        tv_spec = 0.5 * np.abs(spec_counts[i] / n_runs - exact).sum()
        tv_plain = 0.5 * np.abs(plain[i] / n_runs - exact).sum()
        # measured noise floor ~0.11 at 400 runs over a ~25-token nucleus;
        # the control (plain) run shows the same deviation scale
        assert tv_spec < 0.18, (i, tv_spec, tv_plain)
        assert tv_plain < 0.18, (i, tv_plain)


def test_lookup_sampled_accepts_on_peaked_repetitive_stream():
    """tokens/forward > 1 at temperature 0.8 on repetitive text: a model
    with peaked logits (large weight scale) whose continuation the primed
    history predicts accepts most drafts — the sampled mode's payoff."""
    from distributed_llama_tpu.models.params import random_tensors

    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=160)
    host = random_tensors(spec, seed=43, scale=2.5)  # peaked distributions
    eng = _engine(spec, host)
    probe = eng.generate(
        [2, 7], 96, Sampler(spec.vocab_size, 0.0, 0.9, 1,
                            backend="python")).tokens

    eng.reset()
    res = eng.generate_lookup_sampled(
        [2, 7], 96, temperature=0.8, topp=0.9, seed=3, draft_len=7,
        history=[2, 7] + probe)
    fwd, n = eng.last_accept_stats
    assert n == len(res.tokens) == 96
    assert n / fwd > 1.3, (fwd, n)  # measured 1.75 at this scale/seed


def test_lookup_sampled_eos_and_budget():
    """Stop-token truncation inside a confirmed draft and the max_tokens
    cap behave like the greedy path (pos accounts for the truncation)."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=96)
    host, _ = dense_weights(spec, seed=41)
    prompt = [1, 5, 9, 1, 5]

    eng = _engine(spec, host)
    probe = eng.generate_lookup_sampled(prompt, 16, temperature=0.8,
                                        topp=0.9, seed=9).tokens
    assert len(probe) == 16
    eos = probe[5]

    eng2 = _engine(spec, host)
    out = eng2.generate_lookup_sampled(prompt, 16, temperature=0.8,
                                       topp=0.9, seed=9, eos_id=eos).tokens
    assert out == probe[: probe.index(eos) + 1]
    assert eng2.pos == len(prompt) + len(out) - 1

    eng3 = _engine(spec, host)
    assert eng3.generate_lookup_sampled(prompt, 0, temperature=0.8,
                                        topp=0.9, seed=9).tokens == []
    assert eng3.pos == len(prompt)


def _greedy(spec):
    return Sampler(spec.vocab_size, 0.0, 0.9, 1, backend="python")


def _batch_engine(spec, host, b):
    params = load_params(spec, host, mode="q40", dtype=jnp.float32)
    return Engine(spec, params, batch=b, compute_dtype=jnp.float32,
                  cache_dtype=jnp.float32)


@pytest.mark.parametrize("draft_len", [1, 4, 7])
def test_batch_lookup_matches_per_row_greedy(draft_len):
    """Batched speculative decoding (VERDICT r4 #7): ragged per-row drafts
    padded to the widest accept must leave every row's stream EXACTLY its
    single-engine greedy stream — different prompts, different accept
    widths per step."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=96)
    host, _ = dense_weights(spec, seed=41)
    prompts = [[1, 5, 9, 1, 5], [2, 7], [3, 3, 3, 3], [11, 4, 11, 4, 11]]

    want = [
        _engine(spec, host).generate(p, 16, _greedy(spec)).tokens
        for p in prompts
    ]
    eng = _batch_engine(spec, host, 4)
    got = eng.generate_batch_lookup(prompts, 16, draft_len=draft_len)
    assert got == want, draft_len
    fwd, n = eng.last_accept_stats
    assert n == sum(len(w) for w in want)


def test_batch_lookup_eos_budget_and_context_edge():
    """Per-row truncation: one row stops at its eos (included), another is
    capped by the budget, and rows near the context edge must not corrupt
    neighbors (drop-mode OOB writes)."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=48)
    host, _ = dense_weights(spec, seed=43)
    prompts = [[1, 5, 9, 1, 5], [2, 7, 2, 7]]

    probe = [
        _engine(spec, host).generate(p, 12, _greedy(spec)).tokens
        for p in prompts
    ]
    eos = probe[0][2]  # row 0 truncates at its 3rd token
    want = []
    for p in prompts:
        want.append(_engine(spec, host).generate(
            p, 12, _greedy(spec), eos_id=eos).tokens)

    eng = _batch_engine(spec, host, 2)
    got = eng.generate_batch_lookup(prompts, 12, eos_id=eos, draft_len=5)
    assert got == want

    # budget cap of 3: every row emits exactly min(3, its full stream)
    eng2 = _batch_engine(spec, host, 2)
    got3 = eng2.generate_batch_lookup(prompts, 3, draft_len=5)
    assert got3 == [w[:3] if len(w) >= 3 else w for w in probe]

    # budget 0: hard-cap contract
    eng0 = _batch_engine(spec, host, 2)
    assert eng0.generate_batch_lookup(prompts, 0) == [[], []]


def test_batch_lookup_accepts_multiple_tokens_per_forward():
    """The aggregate-throughput claim: on repetitive rows the batch mode
    must confirm > 1 token/forward (the whole point — b rows amortize one
    weight read AND each row advances multiple tokens)."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=160)
    host, _ = dense_weights(spec, seed=43)
    eng0 = _engine(spec, host)
    probe = eng0.generate([2, 7], 96, _greedy(spec)).tokens
    tail = probe[-24:]
    if len(set(tail)) > len(tail) - 4:
        pytest.skip("greedy stream did not become repetitive for this seed")

    eng = _batch_engine(spec, host, 2)
    out = eng.generate_batch_lookup([[2, 7], [2, 7]], 96, draft_len=7)
    assert out == [probe, probe]
    fwd, n = eng.last_accept_stats
    assert n / fwd > 1.5, (fwd, n)  # tokens per forward, summed over rows


def test_batch_lookup_runs_to_context_edge():
    """Rows actually REACH seq_len (code-review r5: the earlier edge test
    never did): with a 24-slot cache and an oversized budget, each row
    must stop exactly where its single-row lookup stream stops, per-row k
    must clamp at the headroom, and the mixed-fill rows must not corrupt
    each other (the scatter's drop-mode OOB writes the padding relies
    on)."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=24)
    host, _ = dense_weights(spec, seed=41)
    prompts = [[1, 5, 9, 1, 5], [2, 7]]  # ragged: row 1 has more headroom

    want = []
    for p in prompts:
        want.append(_engine(spec, host).generate_lookup(
            p, 64, draft_len=7).tokens)
    # sanity: the budget is NOT the binding constraint — the cache is
    # (the final emitted token is never stepped, so a stream can carry one
    # token past the last written slot — generate() parity)
    assert all(len(p) + len(w) <= spec.seq_len + 1
               for p, w in zip(prompts, want))
    assert any(len(p) + len(w) >= spec.seq_len - 1
               for p, w in zip(prompts, want))

    eng = _batch_engine(spec, host, 2)
    got = eng.generate_batch_lookup(prompts, 64, draft_len=7)
    assert got == want


def test_batch_lookup_histories_match_single_row_history():
    """Per-row draft-mining contexts (the bench's fixed-point prime and
    future prefix-reuse serving): histories[i] must behave exactly like
    the single-row stream's history= for that row."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=96)
    host, _ = dense_weights(spec, seed=41)
    prompts = [[1, 5, 9, 1, 5], [2, 7, 2, 7]]
    hists = [[3, 4] + p for p in prompts]

    want = []
    for p, h in zip(prompts, hists):
        want.append(_engine(spec, host).generate_lookup(
            p, 12, draft_len=5, history=h).tokens)
    eng = _batch_engine(spec, host, 2)
    got = eng.generate_batch_lookup(prompts, 12, draft_len=5,
                                    histories=hists)
    assert got == want
