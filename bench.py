"""Benchmark: Llama-2-7B Q40 decode ms/token on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
`vs_baseline` is the speedup over the reference's best published
single-node number for the benched model: Llama-2-7B = 101.81 ms/token
(30-vCPU GCP c3d, ref README.md:88), Llama-3-8B = 564.31 ms/token
(RasPi 5, ref README.md:61), Llama-2-13B = 184.19 ms/token (GCP c3d,
ref README.md:89).

Weights are synthetic Q40 blocks generated at the packed-byte level (random
nibbles + small f16 scales) — decode speed does not depend on weight values,
and this avoids materializing 28 GB of f32 on the host. The decode path is
the production one: Engine.decode_greedy_device (fully on-device lax.scan,
fused argmax, donated KV cache).

Env knobs: BENCH_MODEL=7b|8b|13b|tiny (8b = Llama-3-8B GQA/128k-vocab,
judged against the reference's best 1-node 8B number; 13b vs its 13B GCP
row), BENCH_TOKENS=<n decode steps>, BENCH_SEQ/BENCH_FILL for long-context
variants.
"""

from __future__ import annotations

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from distributed_llama_tpu.models.spec import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.quants.jax_codec import QuantizedTensor
from distributed_llama_tpu.runtime.engine import Engine

BASELINE_MS_PER_TOKEN = 101.81  # ref README.md:88 — Llama 2 7B, 1x GCP c3d-highcpu-30
BASELINE_8B_MS_PER_TOKEN = 564.31  # ref README.md:61 — Llama 3 8B, best 1-node (RasPi 5)
BASELINE_13B_MS_PER_TOKEN = 184.19  # ref README.md:89 — Llama 2 13B, 1x GCP c3d-highcpu-30

LLAMA2_7B = ModelSpec(
    arch=ArchType.LLAMA, dim=4096, hidden_dim=11008, n_layers=32,
    n_heads=32, n_kv_heads=32, vocab_size=32000, seq_len=2048,
    hidden_act=HiddenAct.SILU)

LLAMA2_13B = ModelSpec(  # 7.2 GB packed Q40 — fits one 16 GB chip
    arch=ArchType.LLAMA, dim=5120, hidden_dim=13824, n_layers=40,
    n_heads=40, n_kv_heads=40, vocab_size=32000, seq_len=2048,
    hidden_act=HiddenAct.SILU)

LLAMA3_8B = ModelSpec(  # GQA + 128k vocab (BASELINE.json config 2)
    arch=ArchType.LLAMA, dim=4096, hidden_dim=14336, n_layers=32,
    n_heads=32, n_kv_heads=8, vocab_size=128256, seq_len=2048,
    hidden_act=HiddenAct.SILU, rope_theta=500000.0)

TINY = ModelSpec(
    arch=ArchType.LLAMA, dim=256, hidden_dim=704, n_layers=4,
    n_heads=8, n_kv_heads=8, vocab_size=512, seq_len=256,
    hidden_act=HiddenAct.SILU)


def _rand_q40(rng: np.random.Generator, *shape: int) -> QuantizedTensor:
    """Random Q40 weight of logical shape (..., n): packed nibbles + scales
    sized so dequantized values land in a healthy ~N(0, 0.02) range.
    Generated directly in the device layout (..., 16*nb) flattened; scales
    as uint16 f16-bits as on device (quants/jax_codec.py)."""
    nb = shape[-1] // 32
    packed = rng.integers(0, 256, (*shape[:-1], 16 * nb), dtype=np.uint8)
    scales = (rng.random((*shape[:-1], nb), dtype=np.float32) * 0.004 + 0.001)
    sdt = os.environ.get("BENCH_SCALES", "u16")
    if sdt == "f32":
        return QuantizedTensor(jnp.asarray(packed), jnp.asarray(scales))
    return QuantizedTensor(jnp.asarray(packed),
                           jnp.asarray(scales.astype(np.float16).view(np.uint16)))


def synth_q40_params(spec: ModelSpec, seed: int = 0, dtype=jnp.bfloat16) -> dict:
    rng = np.random.default_rng(seed)
    d, h = spec.dim, spec.hidden_dim
    kv = spec.kv_dim
    layers = []
    for _ in range(spec.n_layers):
        layers.append({
            "rms_att": jnp.ones((d,), jnp.float32),
            "rms_ffn": jnp.ones((d,), jnp.float32),
            "wq": _rand_q40(rng, d, d),
            "wk": _rand_q40(rng, kv, d),
            "wv": _rand_q40(rng, kv, d),
            "wo": _rand_q40(rng, d, d),
            "w1": _rand_q40(rng, h, d),
            "w2": _rand_q40(rng, d, h),
            "w3": _rand_q40(rng, h, d),
        })
    return {
        "tok_emb": jnp.asarray(
            rng.standard_normal((spec.vocab_size, d), dtype=np.float32) * 0.02, dtype),
        "layers": layers,
        "rms_final": jnp.ones((d,), jnp.float32),
        "wcls": _rand_q40(rng, spec.vocab_size, d),
    }


V5E_PEAK_BF16_TFLOPS = 197.0  # per chip; override with BENCH_PEAK_TFLOPS


def _decode_read_bytes(spec: ModelSpec, avg_fill: float = 0.0,
                       cache_itemsize: int = 2) -> int:
    """HBM bytes one decode step must read: every layer weight + wcls in
    packed Q40 form (0.5 B/weight + f16-bit scales on device), one embedding
    row, norms, plus the K/V cache rows attention reads at the average fill
    depth. The roofline denominator for effective-bandwidth."""
    d, h, kv, v = spec.dim, spec.hidden_dim, spec.kv_dim, spec.vocab_size
    per_layer_vals = d * d * 2 + kv * d * 2 + h * d * 2 + d * h
    total_vals = per_layer_vals * spec.n_layers + v * d  # + wcls
    packed = total_vals // 2               # device layout: 16 B per 32 nibbles
    scale_w = 4 if os.environ.get("BENCH_SCALES") == "f32" else 2
    scales = total_vals // 32 * scale_w    # uint16 f16-bit (or A/B f32) scales
    cache = int(avg_fill) * 2 * kv * spec.n_layers * cache_itemsize  # k + v
    return packed + scales + d * 4 * (2 * spec.n_layers + 1) + d * 2 + cache


def _decode_flops(spec: ModelSpec) -> int:
    """MACs*2 per decoded token (matmul weights touched once each)."""
    d, h, kv, v = spec.dim, spec.hidden_dim, spec.kv_dim, spec.vocab_size
    per_layer = d * d * 2 + kv * d * 2 + h * d * 3
    return 2 * (per_layer * spec.n_layers + v * d)


def main() -> None:
    model = os.environ.get("BENCH_MODEL", "7b")
    # 512-token decode: the ~140 ms tunnel dispatch cost amortizes to
    # <0.3 ms/token and attention runs at realistic steady-state fill
    n_tokens = int(os.environ.get("BENCH_TOKENS", "512"))
    spec = {"7b": LLAMA2_7B, "8b": LLAMA3_8B,
            "13b": LLAMA2_13B}.get(model, TINY)
    # long-context variants: BENCH_SEQ widens the cache, BENCH_FILL starts
    # decode at a deep fill (the flash kernel reads ~fill bytes of cache)
    seq = int(os.environ.get("BENCH_SEQ", str(min(spec.seq_len, 2048))))
    fill = int(os.environ.get("BENCH_FILL", "0"))
    assert 0 <= fill < seq - 1, f"BENCH_FILL={fill} must be < BENCH_SEQ-1={seq - 1}"
    if seq != spec.seq_len:
        spec = dataclasses.replace(spec, seq_len=seq)
    # decode must fit the KV cache: decode_greedy_device has no per-step
    # overflow guard, so steps past seq_len would silently measure garbage
    n_tokens = min(n_tokens, seq - fill - 1)

    params = synth_q40_params(spec)
    engine = Engine(
        spec, params,
        compute_dtype=jnp.bfloat16, cache_dtype=jnp.bfloat16,
        max_seq_len=seq)

    # best-of-N: the tunneled platform adds run-to-run jitter of ~1 ms/token
    repeats = int(os.environ.get("BENCH_REPEATS", "2"))
    dt = None
    for _ in range(repeats):
        engine.pos = fill
        _, d = engine.decode_greedy_device(first_token=1, n_tokens=n_tokens)
        dt = d if dt is None else min(dt, d)
    ms_per_token = dt / n_tokens * 1e3

    n_chips = 1
    tok_s = 1000.0 / ms_per_token
    peak_tflops = float(os.environ.get("BENCH_PEAK_TFLOPS",
                                       V5E_PEAK_BF16_TFLOPS))
    eff_bw_gbs = (_decode_read_bytes(spec, avg_fill=fill + n_tokens / 2)
                  / (ms_per_token / 1e3) / 1e9)
    mfu = _decode_flops(spec) * tok_s / (peak_tflops * 1e12)

    metric = {"7b": "llama2_7b_q40_decode_ms_per_token_1chip",
              "8b": "llama3_8b_q40_decode_ms_per_token_1chip",
              "13b": "llama2_13b_q40_decode_ms_per_token_1chip"}.get(
        model, "tiny_llama_q40_decode_ms_per_token")
    base = {"8b": BASELINE_8B_MS_PER_TOKEN,
            "13b": BASELINE_13B_MS_PER_TOKEN}.get(
        model, BASELINE_MS_PER_TOKEN)
    print(json.dumps({
        "metric": metric,
        "value": round(ms_per_token, 3),
        "unit": "ms/token",
        "vs_baseline": round(base / ms_per_token, 2),
        "tokens_per_sec_per_chip": round(tok_s / n_chips, 2),
        "effective_hbm_gbs": round(eff_bw_gbs, 1),
        "mfu": round(mfu, 4),
    }))


if __name__ == "__main__":
    main()
