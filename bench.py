"""Benchmark: Llama-2-7B Q40 decode ms/token on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} — plus a
"variants" list of additional measured rows (prefill throughput, 8k-fill
long-context decode with bf16 and fp8 caches, prompt-lookup speculative
decode, Mixtral-shaped MoE decode) taken in the same run so every
capability axis has on-chip perf evidence.

Outage-proofing (the round-3 driver artifact was lost to a dead TPU
tunnel): the backend is probed in a subprocess with a bounded timeout
BEFORE any jax computation — `jax.devices()` hangs indefinitely when the
axon tunnel is down — and an unavailable backend yields a machine-readable
`{"error": ...}` line instead of a traceback. Each completed row is also
flushed to stderr as it is measured, and a mid-run failure still prints
the final JSON line with every row completed so far plus an "error" field
(`BENCH_PROBE_TIMEOUT` bounds the probe, 0 skips it; `BENCH_PROBE_CODE` /
`BENCH_SIMULATE_OUTAGE` are test hooks for the two failure paths).
`vs_baseline` is the speedup over the reference's best published
single-node number for the benched model: Llama-2-7B = 101.81 ms/token
(30-vCPU GCP c3d, ref README.md:88), Llama-3-8B = 564.31 ms/token
(RasPi 5, ref README.md:61), Llama-2-13B = 184.19 ms/token (GCP c3d,
ref README.md:89). The reference publishes no MoE or long-context numbers
(SURVEY.md §6), so those rows carry vs_baseline: null.

Weights are synthetic Q40 blocks generated at the packed-byte level (random
nibbles + small f16 scales) — decode speed does not depend on weight values,
and this avoids materializing 28 GB of f32 on the host. The decode path is
the production one: Engine.decode_greedy_device (fully on-device lax.scan,
fused argmax, donated KV cache).

Env knobs: BENCH_MODEL=7b|8b|13b|moe|grok|70bt|tiny (8b = Llama-3-8B
GQA/128k-vocab, judged against the reference's best 1-node 8B number; 13b
vs its 13B GCP row; moe/grok = the production-width MoE configs below;
70bt = Llama-2-70B widths truncated to 4 layers — the per-layer cost of
the north-star shape on one chip), BENCH_TOKENS=<n decode steps>,
BENCH_SEQ/BENCH_FILL for long-context variants, BENCH_CACHE=f8 for the fp8
KV cache, BENCH_VARIANTS=0 to skip the extra rows, BENCH_SERVE=1 to add
the continuous-batching Poisson-arrival serving row (_serve_row;
BENCH_SERVE_REQUESTS/_BATCH/_BUDGETS size the trace), BENCH_PREFIX=1 to
add the radix prefix-cache shared-system-prompt row (_prefix_row;
BENCH_PREFIX_REQUESTS/_BATCH/_SYS/_BLOCK/_TOKENS size it), BENCH_CHAOS=1
to add the fault-injection resilience row (_chaos_row), BENCH_ROUTER=1 to
add the 2-replica failover-router row (_router_row; cache-aware vs
round-robin placement + one injected replica kill —
BENCH_ROUTER_REQUESTS/_BATCH/_GROUPS/_SYS/_BLOCK/_BLOCKS/_TOKENS/
_KILL_AFTER size it) plus the PROCESS-mode row (_router_procs_row; two
real replica worker OS processes, one SIGKILLed mid-trace —
respawn-to-routable ms, availability %, zero unstreamed failures, token
parity; BENCH_PROCS_REQUESTS/_TOKENS/_KILL_AFTER/_STEP_MS/
_SPAWN_TIMEOUT size it; BENCH_ROUTER_PROCS=0 skips it, =only runs just
it), and BENCH_AUTOTUNE=1 to add the closed batch-knee-loop row
(_autotune_row: tools/autotune.py calibration -> auto-sized batch ->
SLO-aware adaptive chunk admission, A/B'd against static settings on
goodput-at-SLO with greedy token parity and zero post-warmup compiles;
BENCH_AUTOTUNE_REQUESTS/_TOKENS/_BATCHES/_STATIC/_SLO_TTFT_MS/
_SLO_ITL_MS/_IAT/_LONG size it), BENCH_KVX=1 to add the cross-replica KV
block transfer row (_kvx_row: cold-replica fills OFF vs ON on a
shared-prefix trace — TTFT p50, fill hit rate, wire bytes reconciled —
plus the disaggregated prefill/decode A/B;
BENCH_KVX_FAMILIES/_SYS/_BLOCK/_TOKENS/_IAT/_LONG/_STREAMS size it),
BENCH_FLEET=1 to add the fleet-brain chaos row (_fleet_row: two tenants
through a 10x Poisson spike + one worker SIGKILL under the autoscaling
FleetController — victim p99 TTFT at SLO, replicas visibly scaling,
zero unstreamed failures;
BENCH_FLEET_REQUESTS/_VICTIM/_TOKENS/_STEP_MS/_SLO_MS/_IAT/
_SPAWN_TIMEOUT size it), and
BENCH_VOCAB=1 to add the
vocab-sharding A/B row (_vocab_row: sharded vs replicated embedding+head
on one mixed greedy/sampled trace over a tp mesh — greedy parity
asserted, per-chip embedding+wcls bytes and head+sample ms per variant,
zero frozen-ledger compiles; BENCH_VOCAB_TP/_BATCH/_REQUESTS/_TOKENS/
_STEPS size it), BENCH_SPEC=1 to add the REAL-draft
speculative-decoding row (_spec_row: truncated-depth self-draft vs
prompt-lookup vs plain greedy on a fixed-seed NON-repetitive eval with
the measured accept rate ON the row, plus a Poisson serving A/B with
per-slot drafts under --freeze-compiles semantics;
BENCH_SPEC_TOKENS/_DEPTH/_DRAFT_LEN/_REQUESTS/_BATCH/_TAIL size it).
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np

from distributed_llama_tpu.models.spec import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.quants.jax_codec import QuantizedTensor
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.runtime.trace import TRACER

BASELINE_MS_PER_TOKEN = 101.81  # ref README.md:88 — Llama 2 7B, 1x GCP c3d-highcpu-30
BASELINE_8B_MS_PER_TOKEN = 564.31  # ref README.md:61 — Llama 3 8B, best 1-node (RasPi 5)
BASELINE_13B_MS_PER_TOKEN = 184.19  # ref README.md:89 — Llama 2 13B, 1x GCP c3d-highcpu-30

LLAMA2_7B = ModelSpec(
    arch=ArchType.LLAMA, dim=4096, hidden_dim=11008, n_layers=32,
    n_heads=32, n_kv_heads=32, vocab_size=32000, seq_len=2048,
    hidden_act=HiddenAct.SILU)

LLAMA2_13B = ModelSpec(  # 7.2 GB packed Q40 — fits one 16 GB chip
    arch=ArchType.LLAMA, dim=5120, hidden_dim=13824, n_layers=40,
    n_heads=40, n_kv_heads=40, vocab_size=32000, seq_len=2048,
    hidden_act=HiddenAct.SILU)

LLAMA3_8B = ModelSpec(  # GQA + 128k vocab (BASELINE.json config 2)
    arch=ArchType.LLAMA, dim=4096, hidden_dim=14336, n_layers=32,
    n_heads=32, n_kv_heads=8, vocab_size=128256, seq_len=2048,
    hidden_act=HiddenAct.SILU, rope_theta=500000.0)

TINY = ModelSpec(
    arch=ArchType.LLAMA, dim=256, hidden_dim=704, n_layers=4,
    n_heads=8, n_kv_heads=8, vocab_size=512, seq_len=256,
    hidden_act=HiddenAct.SILU)

MIXTRAL_MOE = ModelSpec(  # Mixtral 8x7B production dims, truncated to 4
    # layers so synth+tunnel-transfer stays bounded (~3.3 GB packed); decode
    # cost is per-layer linear, so ms/token/layer and the active-expert
    # effective bandwidth extrapolate to the full 32-layer model
    arch=ArchType.MIXTRAL, dim=4096, hidden_dim=14336, n_layers=4,
    n_heads=32, n_kv_heads=8, vocab_size=32000, seq_len=2048,
    hidden_act=HiddenAct.SILU, rope_theta=1000000.0,
    n_experts=8, n_active_experts=2)

LLAMA2_70B_TRUNC = ModelSpec(  # Llama-2-70B PRODUCTION widths (dim 8192,
    # hidden 28672, GQA 64/8 — the north-star model), truncated to 4
    # layers (~2.4 GB packed + embeddings): measures the per-layer decode
    # cost of the 70B SHAPE on real silicon, so the v5e-16 projection
    # (README) rests on a measured per-layer number, not the 7B's
    arch=ArchType.LLAMA, dim=8192, hidden_dim=28672, n_layers=4,
    n_heads=64, n_kv_heads=8, vocab_size=32000, seq_len=2048,
    hidden_act=HiddenAct.SILU)

GROK1_TRUNC = ModelSpec(  # Grok-1 PRODUCTION widths (dim 6144, 8 experts
    # of hidden 32768, GQA 48/8, 131k vocab, GELU, the 4-norm block —
    # ref: convert-grok-1.py:59-70 / grok1-tasks.cpp), truncated to 2
    # layers: one full-width layer is 2.72 GB packed Q40, so 2 layers +
    # embeddings (~7.6 GB) saturate a 16 GB chip while ms/token/layer
    # extrapolates to the full 64-layer model (VERDICT r4 #5)
    arch=ArchType.GROK1, dim=6144, hidden_dim=32768, n_layers=2,
    n_heads=48, n_kv_heads=8, vocab_size=131072, seq_len=2048,
    hidden_act=HiddenAct.GELU, rope_theta=10000.0,
    n_experts=8, n_active_experts=2)


def _rand_q40(rng: np.random.Generator, *shape: int) -> QuantizedTensor:
    """Random Q40 weight of logical shape (..., n): packed nibbles + scales
    sized so dequantized values land in a healthy ~N(0, 0.02) range.
    Generated directly in the device layout (..., 16*nb) flattened; scales
    as uint16 f16-bits as on device (quants/jax_codec.py)."""
    nb = shape[-1] // 32
    packed = rng.integers(0, 256, (*shape[:-1], 16 * nb), dtype=np.uint8)
    scales = (rng.random((*shape[:-1], nb), dtype=np.float32) * 0.004 + 0.001)
    sdt = os.environ.get("BENCH_SCALES", "u16")
    if sdt == "f32":
        return QuantizedTensor(jnp.asarray(packed), jnp.asarray(scales))
    return QuantizedTensor(jnp.asarray(packed),
                           jnp.asarray(scales.astype(np.float16).view(np.uint16)))


def synth_q40_params(spec: ModelSpec, seed: int = 0, dtype=jnp.bfloat16) -> dict:
    rng = np.random.default_rng(seed)
    d, h = spec.dim, spec.hidden_dim
    kv = spec.kv_dim
    layers = []
    for _ in range(spec.n_layers):
        lw = {
            "rms_att": jnp.ones((d,), jnp.float32),
            "rms_ffn": jnp.ones((d,), jnp.float32),
            "wq": _rand_q40(rng, d, d),
            "wk": _rand_q40(rng, kv, d),
            "wv": _rand_q40(rng, kv, d),
            "wo": _rand_q40(rng, d, d),
        }
        if spec.arch == ArchType.GROK1:  # the 4-norm Grok block
            lw["rms_moe"] = jnp.ones((d,), jnp.float32)
            lw["rms_ffn2"] = jnp.ones((d,), jnp.float32)
        if spec.is_moe:
            lw["moe_router"] = jnp.asarray(
                rng.standard_normal((spec.n_experts, d), dtype=np.float32)
                * 0.02, dtype)
            lw["moe_up"] = _rand_q40(rng, spec.n_experts, h, d)
            lw["moe_gate"] = _rand_q40(rng, spec.n_experts, h, d)
            lw["moe_down"] = _rand_q40(rng, spec.n_experts, d, h)
        else:
            lw["w1"] = _rand_q40(rng, h, d)
            lw["w2"] = _rand_q40(rng, d, h)
            lw["w3"] = _rand_q40(rng, h, d)
        layers.append(lw)
    return {
        "tok_emb": jnp.asarray(
            rng.standard_normal((spec.vocab_size, d), dtype=np.float32) * 0.02, dtype),
        "layers": layers,
        "rms_final": jnp.ones((d,), jnp.float32),
        "wcls": _rand_q40(rng, spec.vocab_size, d),
    }


V5E_PEAK_BF16_TFLOPS = 197.0  # per chip; override with BENCH_PEAK_TFLOPS


def _ffn_vals_per_layer(spec: ModelSpec) -> int:
    """Q40 values one decode step reads from a layer's FFN: dense = w1/w2/w3;
    MoE = the K active experts' up/gate/down (the gather path reads only the
    active experts' bytes — models/transformer._moe_ffn)."""
    d, h = spec.dim, spec.hidden_dim
    if spec.is_moe:
        return spec.n_active_experts * 3 * h * d
    return 3 * h * d


def _decode_read_bytes(spec: ModelSpec, avg_fill: float = 0.0,
                       cache_itemsize: int = 2) -> int:
    """HBM bytes one decode step must read: every layer weight + wcls in
    packed Q40 form (0.5 B/weight + f16-bit scales on device), one embedding
    row, norms, the f32 MoE router when present, plus the K/V cache rows
    attention reads at the average fill depth. The roofline denominator for
    effective-bandwidth."""
    d, kv, v = spec.dim, spec.kv_dim, spec.vocab_size
    per_layer_vals = d * d * 2 + kv * d * 2 + _ffn_vals_per_layer(spec)
    total_vals = per_layer_vals * spec.n_layers + v * d  # + wcls
    packed = total_vals // 2               # device layout: 16 B per 32 nibbles
    scale_w = 4 if os.environ.get("BENCH_SCALES") == "f32" else 2
    scales = total_vals // 32 * scale_w    # uint16 f16-bit (or A/B f32) scales
    router = (spec.n_experts * d * 2 * spec.n_layers) if spec.is_moe else 0
    cache = int(avg_fill) * 2 * kv * spec.n_layers * cache_itemsize  # k + v
    return (packed + scales + router + cache
            + d * 4 * (2 * spec.n_layers + 1) + d * 2)


def _decode_flops(spec: ModelSpec) -> int:
    """MACs*2 per decoded token (active matmul weights touched once each)."""
    d, kv, v = spec.dim, spec.kv_dim, spec.vocab_size
    per_layer = d * d * 2 + kv * d * 2 + _ffn_vals_per_layer(spec)
    return 2 * (per_layer * spec.n_layers + v * d)


def _measure_decode(engine, n_tokens: int, fill: int, repeats: int) -> float:
    """Best-of-N decode timing (the tunneled platform adds run-to-run jitter
    of ~1 ms/token); returns ms/token."""
    dt = None
    for _ in range(repeats):
        engine.pos = fill
        _, d = engine.decode_greedy_device(first_token=1, n_tokens=n_tokens)
        dt = d if dt is None else min(dt, d)
        if TRACER.enabled:
            # the on-device loop has no per-step host boundary, so the
            # timeline sample is the run's MEAN ms/token at this batch
            # composition — one sample per measured run, comparable with
            # the scheduler rows' per-iteration records
            TRACER.step(decode_rows=engine.batch, prefill_rows=0, chunk=0,
                        queue_depth=0, wall_ms=d / n_tokens * 1e3)
    return dt / n_tokens * 1e3


# hbm-block plumbing (ISSUE-10 satellite): row functions that build an
# engine note it here; _with_step_timeline attaches the ledger next to
# step_timeline on every emitted row. A box, not a parameter, because
# the engines live deep inside the row functions.
_HBM_BOX: dict = {}


def _note_hbm(engine, prefix_cache=None) -> None:
    """Record the hbm ledger (runtime/profiler.hbm_ledger) of the row's
    engine — called while the engine's arrays are still live."""
    from distributed_llama_tpu.runtime.profiler import hbm_ledger

    try:
        _HBM_BOX["hbm"] = hbm_ledger(engine, prefix_cache)
    except Exception as e:  # noqa: BLE001 — a ledger bug must never
        _HBM_BOX["hbm"] = {"error": f"{type(e).__name__}: {e}"}  # kill a
        # measured row


def _with_step_timeline(row_fn, *args, **kwargs) -> dict:
    """Run one bench row with the flight recorder on and attach the
    per-batch-composition step-ms summary (the ISSUE-9 satellite: every
    row carries the raw measurement ROADMAP item 1's knee search mines).
    Rows that drive the slot scheduler get real per-iteration
    compositions; rows measuring the on-device decode loop get per-run
    mean samples (see _measure_decode); the cluster control-plane row
    records its heartbeat round trips under the dec0_pre0_c0
    composition (its "step" is one PING→PONG). The recorder is reset
    per row so compositions from different models/batches never mix."""
    TRACER.reset()
    # decode_every huge: the serving rows only need STEP records here —
    # span events would grow the ring without changing the block
    TRACER.configure(capacity=4096, decode_every=1 << 30)
    _HBM_BOX.pop("hbm", None)
    try:
        row = row_fn(*args, **kwargs)
    finally:
        timeline = TRACER.steps.summary_json()
        TRACER.reset()
    row["step_timeline"] = timeline
    # the hbm ledger the row noted while its engine was live (empty for
    # rows without one — the cluster control-plane row; the procs row
    # merges WORKER-side ledgers itself)
    row.setdefault("hbm", _HBM_BOX.pop("hbm", {}))
    return row


def _decode_row(metric: str, spec: ModelSpec, ms_per_token: float, *,
                fill: int = 0, n_tokens: int = 0, cache_itemsize: int = 2,
                base: float | None = None) -> dict:
    tok_s = 1000.0 / ms_per_token
    peak_tflops = float(os.environ.get("BENCH_PEAK_TFLOPS",
                                       V5E_PEAK_BF16_TFLOPS))
    eff_bw_gbs = (_decode_read_bytes(spec, avg_fill=fill + n_tokens / 2,
                                     cache_itemsize=cache_itemsize)
                  / (ms_per_token / 1e3) / 1e9)
    mfu = _decode_flops(spec) * tok_s / (peak_tflops * 1e12)
    return {
        "metric": metric,
        "value": round(ms_per_token, 3),
        "unit": "ms/token",
        "vs_baseline": round(base / ms_per_token, 2) if base else None,
        "tokens_per_sec_per_chip": round(tok_s, 2),
        "effective_hbm_gbs": round(eff_bw_gbs, 1),
        "mfu": round(mfu, 4),
    }


def _measure_prefill(engine, n_prompt: int, repeats: int) -> float:
    """Time a whole-prompt chunked prefill from a fresh session; returns
    tok/s (first run compiles and is excluded)."""
    import time

    rng = np.random.default_rng(7)
    prompt = rng.integers(
        1, engine.spec.vocab_size, n_prompt).astype(np.int64).tolist()
    best = None
    for i in range(repeats + 1):
        engine.reset()
        t0 = time.perf_counter()
        logits = engine.prefill(prompt)
        np.asarray(logits)  # D2H is the only true sync on tunneled platforms
        dt = time.perf_counter() - t0
        if i > 0:
            best = dt if best is None else min(best, dt)
    engine.reset()
    return n_prompt / best


def _platform_pin() -> str:
    """BENCH_PLATFORM pins the jax platform at the CONFIG level (a
    sitecustomize hook may pin the TPU plugin there, making the
    JAX_PLATFORMS env var insufficient — measured repo finding). Used by
    tests to run the whole bench, probe included, on cpu; the driver
    leaves it unset and gets the default (TPU) platform resolution."""
    plat = os.environ.get("BENCH_PLATFORM", "")
    if not all(c.isalnum() or c == "," for c in plat):  # interpolated into
        raise ValueError(f"bad BENCH_PLATFORM: {plat!r}")  # child code
    return (f"jax.config.update('jax_platforms', '{plat}'); " if plat
            else "")


def _probe_backend() -> str | None:
    """Bounded-timeout backend liveness probe, run in a subprocess because
    `jax.devices()` HANGS (not errors) when the axon TPU tunnel is down —
    a timeout-killed child is the only reliable detection. Returns None
    when the default backend comes up, else a diagnostic string.
    BENCH_PROBE_TIMEOUT seconds (default 120 — plugin init on a live
    tunnel takes ~10-40 s), 0 skips the probe entirely; BENCH_PROBE_CODE
    overrides the probed statement (test hook for simulating a hung
    plugin)."""
    timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "120"))
    if timeout <= 0:
        return None
    code = os.environ.get(
        "BENCH_PROBE_CODE",
        "import jax; " + _platform_pin() +
        "print(jax.devices()[0].platform)")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                           capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return f"probe timed out after {timeout:.0f}s (axon tunnel down?)"
    if r.returncode != 0:
        return f"probe failed rc={r.returncode}: {r.stderr.strip()[-300:]}"
    return None


def _lookup_row(engine, repeats: int) -> dict:
    """Prompt-lookup speculative decode on the 7B engine: host-loop wall
    of a 128-token plain greedy run vs the same run through
    `generate_lookup` with the draft miner's history primed with the
    model's own (deterministic, fixed-seed) continuation — the full-
    acceptance regime repetitive text reaches, measured with the real
    mechanism live (mining, verify forwards, acceptance). Reported
    fields: end-to-end speedup, tokens/forward, and the cost of a
    width-8 verify forward relative to a single-token step. Acceptance is
    content-dependent; this row is the mechanism's ceiling, not a corpus
    average.

    Parity note: in bf16 the t = 1 and t = 1+k forwards tile differently,
    and an argmax near-tie can flip a token (both streams are the model's
    own argmaxes; exact-parity is asserted by the f32 suite,
    tests/test_speculative.py). The timed prime is therefore the lookup
    stream's own FIXED POINT — re-primed until it reproduces itself — so
    the row measures full acceptance; `parity_prefix` records how far the
    plain stream agreed."""
    import time

    from distributed_llama_tpu.sampler import Sampler

    n, draft_len = 128, 7
    prompt = [1, 17, 93, 5]
    greedy = Sampler(engine.spec.vocab_size, temperature=0.0, topp=0.9,
                     seed=1)

    best_plain, plain_tokens = None, None
    for i in range(repeats + 1):  # run 0 compiles — excluded
        engine.reset()
        t0 = time.perf_counter()
        r = engine.generate(prompt, max_tokens=n, sampler=greedy)
        dt = time.perf_counter() - t0
        if i > 0:
            best_plain = dt if best_plain is None else min(best_plain, dt)
        plain_tokens = r.tokens

    stream = plain_tokens
    for _ in range(4):  # fixed-point prime (converges in 1-2 passes)
        engine.reset()
        lk = engine.generate_lookup(prompt, n, draft_len=draft_len,
                                    history=prompt + stream).tokens
        if lk == stream:
            break
        stream = lk

    primed = prompt + stream
    best_lk, lk_tokens = None, None
    for i in range(repeats + 1):
        engine.reset()
        t0 = time.perf_counter()
        r = engine.generate_lookup(prompt, n, draft_len=draft_len,
                                   history=primed)
        dt = time.perf_counter() - t0
        if i > 0:
            best_lk = dt if best_lk is None else min(best_lk, dt)
        lk_tokens = r.tokens
    forwards, toks = engine.last_accept_stats
    agree = next((i for i, (a, b) in enumerate(zip(plain_tokens, lk_tokens))
                  if a != b), len(lk_tokens))
    engine.reset()

    spec_rec = getattr(engine, "last_spec",
                       {"drafted": 0, "accepted": 0})
    row = {
        "metric": "llama2_7b_q40_lookup_decode_hostloop_speedup_max_accept",
        "value": round(best_plain / best_lk, 2), "unit": "x",
        "vs_baseline": None,
        "tokens_per_forward": round(toks / forwards, 2),
        # honest accept reporting (VERDICT #6): the measured rate and
        # the regime label ride the row — this trace is REPETITIVE BY
        # CONSTRUCTION (fixed-point primed history = the mechanism's
        # ceiling); the non-repetitive regime is BENCH_SPEC's _spec_row
        "accept_rate": round(spec_rec["accepted"]
                             / max(spec_rec["drafted"], 1), 3),
        "eval_label": "repetitive_primed",
        "verify8_cost_vs_step": round((best_lk / forwards)
                                      / (best_plain / n), 2),
        "parity_prefix": round(agree / n, 3),
    }
    if toks / forwards <= 1.2:
        # a degenerate synth stream can defeat even the primed miner; the
        # row degrades with a warning rather than aborting later rows
        row["warning"] = "low acceptance despite primed history"
    return row


def _batch_row(params, spec: ModelSpec, repeats: int, b: int = 8) -> dict:
    """Batched decode aggregate throughput on ONE chip: decode is
    weight-read-bound at batch=1, so b rows amortize the same weight read
    across b tokens — the single-chip serving-throughput headline the
    batched API endpoint rides on. Measured through the ON-DEVICE batched
    loop (generate_batch_device — one dispatch for the whole run): the
    host-loop batch path pays the tunnel's ~140 ms per step on this
    platform, which would measure the tunnel, not the amortization."""
    import gc
    import time

    eng = Engine(spec, params, compute_dtype=jnp.bfloat16,
                 cache_dtype=jnp.bfloat16, max_seq_len=512, batch=b)
    n = 96
    prompts = [[1, 17 + i, 93, 5 + i] for i in range(b)]
    best = None
    for i in range(repeats + 1):  # run 0 compiles — excluded
        eng.reset()
        t0 = time.perf_counter()
        outs = eng.generate_batch_device(
            prompts, n, temperature=0.8, topp=0.9, seed=9)
        dt = time.perf_counter() - t0
        if i > 0:
            best = dt if best is None else min(best, dt)
    toks = sum(len(o) for o in outs)
    agg_tok_s = toks / best
    del eng
    gc.collect()
    return {
        "metric": f"llama2_7b_q40_batch{b}_device_decode_agg_tok_per_s_1chip",
        "value": round(agg_tok_s, 1), "unit": "tok/s",
        "vs_baseline": None,
        "ms_per_step": round(best / (toks / b) * 1e3, 3),
        "batch": b,
    }


def _batch_lookup_row(params, spec: ModelSpec, repeats: int,
                      b: int = 8) -> dict:
    """Batched SPECULATIVE decode (VERDICT r4 #7): b rows amortize one
    weight read per verify forward AND each row confirms multiple draft
    tokens per forward — the two serving multipliers compose. Same
    max-acceptance regime as _lookup_row (per-row histories primed with
    each row's own fixed-point continuation); the host loop pays the
    tunnel dispatch per forward, but multi-token accepts mean ~1/k the
    forwards of the plain batch loop."""
    import gc
    import time

    eng = Engine(spec, params, compute_dtype=jnp.bfloat16,
                 cache_dtype=jnp.bfloat16, max_seq_len=512, batch=b)
    n, draft_len = 96, 7
    prompts = [[1, 17 + i, 93, 5 + i] for i in range(b)]

    # per-row fixed-point prime (the _lookup_row discipline, batched)
    streams = eng.generate_batch_lookup(prompts, n, draft_len=draft_len)
    for _ in range(4):
        eng.reset()
        nxt = eng.generate_batch_lookup(
            prompts, n, draft_len=draft_len,
            histories=[p + s for p, s in zip(prompts, streams)])
        if nxt == streams:
            break
        streams = nxt
    primed = [p + s for p, s in zip(prompts, streams)]

    best = None
    outs = None
    for i in range(repeats + 1):  # run 0 warms remaining widths
        eng.reset()
        t0 = time.perf_counter()
        outs = eng.generate_batch_lookup(prompts, n, draft_len=draft_len,
                                         histories=primed)
        dt = time.perf_counter() - t0
        if i > 0:
            best = dt if best is None else min(best, dt)
    forwards, toks = eng.last_accept_stats
    agg_tok_s = sum(len(o) for o in outs) / best
    del eng
    gc.collect()
    return {
        "metric": (f"llama2_7b_q40_batch{b}_lookup_decode_agg_tok_per_s_"
                   "1chip_max_accept"),
        "value": round(agg_tok_s, 1), "unit": "tok/s",
        "vs_baseline": None,
        "tokens_per_forward_all_rows": round(toks / forwards, 2),
        # VERDICT #6 labeling: fixed-point primed == repetitive by
        # construction (see _lookup_row; _spec_row is the other regime)
        "eval_label": "repetitive_primed",
        "batch": b,
    }


def _serve_row(params, spec: ModelSpec, prefix: str, b: int = 8) -> dict:
    """Continuous batching vs static batching under a Poisson arrival
    trace (the ISSUE-2 serving metric). One fixed-seed synthetic trace of
    mixed-length requests arrives at ~system capacity; it is served twice:

      * STATIC — the old /v1/batch/completions regime: requests group into
        full batches of `b` in arrival order, a batch starts only when its
        LAST member has arrived and the previous batch drained, and every
        slot is held until the batch's slowest row finishes its budget
        (per-row budgets retire rows via stop_flags; the host-loop
        generate_batch_stream is the production static path).
      * CONTINUOUS — the slot scheduler (runtime/scheduler.py): requests
        join the running decode batch on arrival, chunked prefill
        interleaves with decode, finished rows free their slot instantly.

    Both are host-loop paths over the same engine weights, so the ratio
    isolates the SCHEDULING win (slot reuse + no wait-for-full-batch), not
    dispatch differences. Batch durations for the static fold are measured
    wall-clock; arrivals are folded analytically so the static number
    never pays sleep jitter. Reported: continuous aggregate tok/s (the
    headline), the static number and ratio, and the scheduler's TTFT/ITL
    percentiles + occupancy from runtime/stats.ServeStats.

    Env knobs: BENCH_SERVE_REQUESTS (default 24), BENCH_SERVE_BATCH
    (default 8), BENCH_SERVE_BUDGETS (comma list, default 16,32,64,96).
    Prompt lengths cycle {8, 16, 32} so the static path's right-padded
    prefill keeps a bounded compile-key set, like the scheduler's fixed
    chunk."""
    import gc
    import time

    from distributed_llama_tpu.runtime.scheduler import Scheduler
    from distributed_llama_tpu.sampler import Sampler

    b = int(os.environ.get("BENCH_SERVE_BATCH", str(b)))
    n_req = max(int(os.environ.get("BENCH_SERVE_REQUESTS", "24")), b)
    budgets_pool = [int(x) for x in os.environ.get(
        "BENCH_SERVE_BUDGETS", "16,32,64,96").split(",")]
    seq = min(512, spec.seq_len)
    cdt = jnp.float32 if jax.default_backend() == "cpu" else jnp.bfloat16

    rng = np.random.default_rng(0)
    lens = [(8, 16, 32)[i % 3] for i in range(n_req)]
    prompts = [rng.integers(1, spec.vocab_size, n).astype(np.int64).tolist()
               for n in lens]
    budgets = [budgets_pool[int(i)] for i in
               rng.integers(0, len(budgets_pool), n_req)]

    eng = Engine(spec, params, compute_dtype=cdt, cache_dtype=cdt,
                 max_seq_len=seq, batch=b)
    _note_hbm(eng)

    def greedy():
        return Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=7)

    def run_static_batch(batch_prompts, batch_budgets):
        """One wait-for-full-batch run with per-row budget retirement;
        returns (tokens, seconds)."""
        n_rows = len(batch_prompts)
        rows = batch_prompts + [[1]] * (eng.batch - n_rows)
        stop_flags = np.zeros(eng.batch, bool)
        stop_flags[n_rows:] = True
        counts = [0] * n_rows
        eng.reset()
        t0 = time.perf_counter()
        for step in eng.generate_batch_stream(rows, max(batch_budgets),
                                              greedy(),
                                              stop_flags=stop_flags):
            for i in range(n_rows):
                if step[i] is not None:
                    counts[i] += 1
                    if counts[i] >= batch_budgets[i]:
                        stop_flags[i] = True
        return sum(counts), time.perf_counter() - t0

    # warm every compile key off the clock: static bpre widths {8,16,32} +
    # bvec, and the scheduler's slot_prefill_chunk_32 + slot_decode_step
    for n in (8, 16, 32):
        wp = rng.integers(1, spec.vocab_size, n).astype(np.int64).tolist()
        run_static_batch([wp] * min(2, b), [2] * min(2, b))
    sched = Scheduler(eng, chunk=32)
    warm = sched.submit(prompts[0], 2, greedy())
    while not warm.finished.is_set():
        sched.step()

    # static fold: batches of b in arrival order; batch k starts at
    # max(previous end, last member's arrival)
    d_static = []
    toks_static = 0
    for i in range(0, n_req, b):
        t, d = run_static_batch(prompts[i:i + b], budgets[i:i + b])
        toks_static += t
        d_static.append(d)

    # offered load = 3x the STATIC path's measured capacity — the
    # saturated ("heavy traffic") regime where aggregate throughput, not
    # arrival rate, is the binding constraint. Under lighter load both
    # systems simply track arrivals and the comparison collapses to
    # latency (where continuous wins on TTFT but the tok/s ratio is ~1);
    # saturation is what exposes static batching's idle-slot waste.
    mean_iat = sum(d_static) / n_req / 3.0
    arrivals = np.cumsum(rng.exponential(mean_iat, n_req))
    end = 0.0
    for k, d in enumerate(d_static):
        last_arrival = arrivals[min((k + 1) * b, n_req) - 1]
        end = max(end, last_arrival) + d
    static_tok_s = toks_static / end

    # continuous run on the same trace, real wall clock
    sched = Scheduler(eng, chunk=32)
    sched.start()
    try:
        live = []
        t0 = time.perf_counter()
        for arr, p, k in zip(arrivals, prompts, budgets):
            dt = t0 + arr - time.perf_counter()
            if dt > 0:
                time.sleep(dt)
            live.append(sched.submit(p, k, greedy()))
        for r in live:
            assert r.finished.wait(600), "scheduler stalled"
        t_cont = time.perf_counter() - t0
    finally:
        sched.close()
    toks_cont = sum(r.stats.n_out for r in live)
    cont_tok_s = toks_cont / t_cont
    s = sched.stats.summary()

    del eng
    gc.collect()
    return {
        "metric": f"{prefix}_continuous_batch{b}_poisson_agg_tok_per_s_1chip",
        "value": round(cont_tok_s, 1), "unit": "tok/s", "vs_baseline": None,
        "static_agg_tok_per_s": round(static_tok_s, 1),
        "vs_static_batch": round(cont_tok_s / static_tok_s, 2),
        "requests": n_req, "batch": b,
        "tokens": toks_cont,
        "ttft_p50_ms": s["ttft_p50_ms"], "ttft_p99_ms": s["ttft_p99_ms"],
        "itl_p50_ms": s["itl_p50_ms"], "itl_p99_ms": s["itl_p99_ms"],
        "mean_slot_occupancy": s["mean_slot_occupancy"],
        "max_queue_depth": s["max_queue_depth"],
    }


def _prefix_row(params, spec: ModelSpec, prefix: str, b: int = 4) -> dict:
    """Radix prefix cache under a shared-system-prompt workload (the
    ISSUE-4 metric): replay a fixed-seed Poisson arrival trace whose
    prompts share a common system prefix — the dominant production
    chat/RAG shape — through the slot scheduler twice, cache OFF then
    ON (runtime/prefix_cache.py), and report:

      * prefill tokens served from cache (the headline %, acceptance
        bar >= 50 on this workload),
      * greedy TOKEN PARITY between the runs (seeded K/V is bitwise the
        cold prefill's K/V, so outputs must be identical),
      * TTFT p50 delta — the latency a returning client actually gains
        when its system prompt + history seed instead of prefilling,
      * the modeled wire/HBM tradeoff (netstats.estimate_prefix_reuse).

    The FIRST request runs alone before the measured replay (cache ON
    and OFF both, for symmetry): a shared system prompt is warm long
    before any steady-state window, and publishing happens at
    prefill-finish, so the replayed requests all see a warm tree.

    Env knobs: BENCH_PREFIX_REQUESTS (default 16), BENCH_PREFIX_BATCH
    (default 4), BENCH_PREFIX_SYS (shared prefix tokens, default 48),
    BENCH_PREFIX_BLOCK (block_len, default 16 — the shared prefix is a
    whole number of blocks so the whole-blocks-only lookup covers it),
    BENCH_PREFIX_TOKENS (per-request decode budget, default 8)."""
    import gc
    import time

    from distributed_llama_tpu.runtime.netstats import estimate_prefix_reuse
    from distributed_llama_tpu.runtime.prefix_cache import PrefixCache
    from distributed_llama_tpu.runtime.scheduler import Scheduler
    from distributed_llama_tpu.sampler import Sampler

    b = int(os.environ.get("BENCH_PREFIX_BATCH", str(b)))
    n_req = max(int(os.environ.get("BENCH_PREFIX_REQUESTS", "16")), 2)
    sys_len = int(os.environ.get("BENCH_PREFIX_SYS", "48"))
    bl = int(os.environ.get("BENCH_PREFIX_BLOCK", "16"))
    budget = int(os.environ.get("BENCH_PREFIX_TOKENS", "8"))
    seq = min(512, spec.seq_len)
    cdt = jnp.float32 if jax.default_backend() == "cpu" else jnp.bfloat16

    rng = np.random.default_rng(0)
    shared = rng.integers(1, spec.vocab_size, sys_len).astype(
        np.int64).tolist()
    tails = [rng.integers(1, spec.vocab_size, (8, 12, 16)[i % 3]).astype(
        np.int64).tolist() for i in range(n_req)]
    prompts = [shared + t for t in tails]
    arrivals = np.cumsum(rng.exponential(0.04, n_req - 1))

    eng = Engine(spec, params, compute_dtype=cdt, cache_dtype=cdt,
                 max_seq_len=seq, batch=b)

    def greedy():
        return Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=7)

    def run_trace(pc):
        """One full serve of the trace; returns (per-request token lists,
        replayed-requests TTFT p50 ms)."""
        sched = Scheduler(eng, chunk=bl, prefix_cache=pc)
        sched.warmup()  # compile keys (incl. seed/publish) off the clock
        prime = sched.submit(prompts[0], budget, greedy())
        while not prime.finished.is_set():
            sched.step()
        sched.start()
        live = []
        try:
            t0 = time.perf_counter()
            for arr, p in zip(arrivals, prompts[1:]):
                dt = t0 + arr - time.perf_counter()
                if dt > 0:
                    time.sleep(dt)
                live.append(sched.submit(p, budget, greedy()))
            for r in live:
                assert r.finished.wait(600), "scheduler stalled"
        finally:
            sched.close()
        outs = [list(prime.tokens(timeout=5.0))]
        outs += [list(r.tokens(timeout=5.0)) for r in live]
        ttfts = sorted(r.stats.ttft_ms for r in live)
        return outs, ttfts[len(ttfts) // 2]

    outs_off, ttft_off = run_trace(None)
    pc = PrefixCache(eng, num_blocks=max(2 * b * seq // bl,
                                         sys_len // bl + 8), block_len=bl)
    _note_hbm(eng, pc)  # the cache-ON shape: slots + the real arena
    outs_on, ttft_on = run_trace(pc)

    s = pc.stats.summary()
    # hbm_copy uses the REAL copied volume: every hit gathers the full
    # fixed seed width (seq // bl blocks), not just the matched tokens —
    # the single-compilation-key tradeoff estimate_prefix_reuse documents
    reuse = estimate_prefix_reuse(spec, eng.mesh,
                                  tokens_saved=s["tokens_saved"],
                                  tokens_copied=s["hits"] * (seq // bl) * bl,
                                  cache_bytes=jnp.dtype(cdt).itemsize)
    del eng
    gc.collect()
    return {
        "metric": f"{prefix}_prefix_cache_block{bl}_prefill_saved_pct",
        "value": round(100.0 * (s["prefill_saved_frac"] or 0.0), 2),
        "unit": "%", "vs_baseline": None,
        "requests": n_req, "batch": b,
        "shared_prefix_tokens": sys_len, "block_len": bl,
        "token_parity": outs_on == outs_off,
        "hit_rate": s["hit_rate"],
        "tokens_saved": s["tokens_saved"],
        "blocks_published": s["blocks_published"],
        "evictions": s["evictions"],
        "ttft_p50_ms_off": round(ttft_off, 3),
        "ttft_p50_ms_on": round(ttft_on, 3),
        "ttft_p50_delta_ms": round(ttft_off - ttft_on, 3),
        **reuse,
    }


def _autotune_row(params, spec: ModelSpec, prefix: str) -> dict:
    """The closed batch-knee loop, measured end to end (the ISSUE-11
    metric): calibrate → auto-size → self-tune, A/B'd against hand-tuned
    static settings on ONE fixed-seed Poisson trace.

      1. CALIBRATE — tools/autotune.calibrate() sweeps the serving step
         shapes across BENCH_AUTOTUNE_BATCHES (reusing this run's
         synthesized weights) and fits the knee; the artifact rides the
         row under "calibration".
      2. AUTO-SIZE — runtime/profiler.resolve_auto_shape picks
         --serve-batch from the calibrated knee capped by HBM headroom
         (null on CPU: the knee stands alone), exactly what
         `--serve-batch auto --autotune AUTOTUNE.json` does at startup.
      3. SELF-TUNE — the trace is served by the auto-sized scheduler
         with the SLO-aware adaptive chunk policy armed
         (--slo-ttft-ms/--slo-itl-ms) and --freeze-compiles semantics
         enforced (COMPILES.freeze during the run), vs every static
         (batch, chunk) combo in BENCH_AUTOTUNE_STATIC.

    The trace interleaves short decode-heavy requests with LONG prompts
    (the chunked-prefill interference shape): a wide static chunk blows
    running streams' ITL whenever a long prompt admits, a narrow one
    starves TTFT — the adaptive ladder is the tradeoff knob. Reported
    per policy: goodput-at-SLO (tokens of SLO-meeting requests / wall —
    dlprof's goodput definition), SLO fraction, TTFT/ITL p50/p99, and
    aggregate tok/s. Acceptance bars ride the row: `beats_all_static`
    (goodput-at-SLO >= every swept static), `token_parity` (greedy
    outputs bit-identical across ALL policies — slot scheduling and
    chunk boundaries must not change tokens), and
    `compiles_after_warmup == 0` across the adaptive run (the width
    ladder is warmed up front; the sentinel proves it).

    Env knobs: BENCH_AUTOTUNE_REQUESTS (default 24),
    BENCH_AUTOTUNE_TOKENS (short-request budget, default 16),
    BENCH_AUTOTUNE_BATCHES (calibration sweep, default "2,4,8,16,32"),
    BENCH_AUTOTUNE_STATIC (static B:C combos, default
    "2:32,4:32,8:8,8:32" — 8 is the hand-picked production batch this
    loop was built to beat), BENCH_AUTOTUNE_SLO_TTFT_MS /
    _SLO_ITL_MS (defaults 1000/80 — CPU-tiny scale),
    BENCH_AUTOTUNE_REPEATS (best-of-N serves per policy, default 2),
    BENCH_AUTOTUNE_IAT (mean arrival gap s, default 0.02 — saturates
    every swept static so goodput, not arrivals, is the binding
    constraint, the _serve_row discipline),
    BENCH_AUTOTUNE_LONG (long-prompt tokens, default 96)."""
    import gc
    import time

    from distributed_llama_tpu.runtime.profiler import (COMPILES,
                                                        resolve_auto_shape)
    from distributed_llama_tpu.runtime.scheduler import Scheduler
    from distributed_llama_tpu.sampler import Sampler

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    import autotune as autotune_mod

    n_req = max(int(os.environ.get("BENCH_AUTOTUNE_REQUESTS", "24")), 4)
    budget = int(os.environ.get("BENCH_AUTOTUNE_TOKENS", "16"))
    cal_batches = [int(x) for x in os.environ.get(
        "BENCH_AUTOTUNE_BATCHES", "2,4,8,16,32").split(",")]
    statics = [tuple(int(v) for v in s.split(":")) for s in os.environ.get(
        "BENCH_AUTOTUNE_STATIC", "2:32,4:32,8:8,8:32").split(",")]
    slo_ttft = float(os.environ.get("BENCH_AUTOTUNE_SLO_TTFT_MS", "1000"))
    slo_itl = float(os.environ.get("BENCH_AUTOTUNE_SLO_ITL_MS", "80"))
    mean_iat = float(os.environ.get("BENCH_AUTOTUNE_IAT", "0.02"))
    long_len = int(os.environ.get("BENCH_AUTOTUNE_LONG", "96"))
    chunk_max = 32
    seq = min(256, spec.seq_len)
    cdt = jnp.float32 if jax.default_backend() == "cpu" else jnp.bfloat16

    # 1. CALIBRATE (quiet: the sweep's own step timelines are internal)
    artifact = autotune_mod.calibrate(
        model=os.environ.get("BENCH_MODEL", "tiny"), batches=cal_batches,
        chunk=chunk_max, steps=16, seq=seq, spec=spec, params=params,
        log=lambda *a, **k: None)
    # calibrate() drives its own recorder sessions; re-arm the row's
    # (dropping the sweep's compositions — the A/B serves below are the
    # row's step_timeline)
    TRACER.reset()
    TRACER.configure(capacity=4096, decode_every=1 << 30)

    # 2. AUTO-SIZE from the artifact, the way --serve-batch auto does
    template = Engine(spec, params, compute_dtype=cdt, cache_dtype=cdt,
                      max_seq_len=seq, batch=1)
    autosize = resolve_auto_shape(template, serve_batch="auto",
                                  autotune=artifact, slo_itl_ms=slo_itl)
    del template
    gc.collect()
    b_auto = autosize["serve_batch"]

    # the fixed-seed trace: every 3rd request a long prompt, the rest
    # short decode-heavy streams (arrivals saturate the smallest static)
    rng = np.random.default_rng(0)
    lens = [long_len if i % 3 == 2 else (6, 10)[i % 2]
            for i in range(n_req)]
    budgets = [max(budget // 2, 4) if i % 3 == 2 else budget
               for i in range(n_req)]
    prompts = [rng.integers(1, spec.vocab_size, n).astype(np.int64).tolist()
               for n in lens]
    arrivals = np.cumsum(rng.exponential(mean_iat, n_req))

    def greedy():
        return Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=7)

    repeats = max(int(os.environ.get("BENCH_AUTOTUNE_REPEATS", "2")), 1)

    def run_policy(b: int, chunk: int, adaptive: bool) -> dict:
        """Serve the trace `repeats` times under one policy and keep the
        best-of-N goodput (the bench's jitter discipline — every policy
        gets the same treatment, so the A/B compares policies, not CPU
        scheduling luck). Token outputs must be IDENTICAL across the
        repeats (asserted) — timing never changes greedy tokens."""
        eng = Engine(spec, params, compute_dtype=cdt, cache_dtype=cdt,
                     max_seq_len=seq, batch=b)
        best = None
        for rep in range(repeats):
            # fresh scheduler per repeat over the SAME engine: the
            # compile keys are warm after the first, and slot reuse
            # needs no cache reset (overwrite-before-attend)
            sched = Scheduler(eng, chunk=chunk,
                              slo_ttft_ms=slo_ttft if adaptive else None,
                              slo_itl_ms=slo_itl if adaptive else None)
            sched.warmup()
            if adaptive and rep == 0:
                _note_hbm(eng)  # the auto-sized shape is the row's ledger
            sched.start()
            live = []
            try:
                t0 = time.perf_counter()
                for arr, p, k in zip(arrivals, prompts, budgets):
                    dt = t0 + arr - time.perf_counter()
                    if dt > 0:
                        time.sleep(dt)
                    live.append(sched.submit(p, k, greedy()))
                for r in live:
                    assert r.finished.wait(600), "scheduler stalled"
                wall = time.perf_counter() - t0
            finally:
                admission = (sched.admission.summary()
                             if sched.admission is not None else None)
                sched.close()
            outs = [list(r.tokens(timeout=5.0)) for r in live]
            recs = [r.stats for r in live]
            ok = [r for r in recs
                  if (r.ttft_ms is not None and r.ttft_ms <= slo_ttft
                      and (r.itl_ms is None or r.itl_ms <= slo_itl))]
            ttfts = sorted(r.ttft_ms for r in recs
                           if r.ttft_ms is not None)
            itls = sorted(r.itl_ms for r in recs if r.itl_ms is not None)
            pct = lambda xs, p: (round(xs[min(len(xs) - 1,  # noqa: E731
                                              round(p * (len(xs) - 1)))],
                                       3) if xs else None)
            run = {
                "batch": b, "chunk": chunk, "adaptive": adaptive,
                "goodput_tok_s": round(sum(r.n_out for r in ok) / wall, 2),
                "agg_tok_s": round(sum(r.n_out for r in recs) / wall, 2),
                "slo_fraction": round(len(ok) / len(recs), 4),
                "ttft_p50_ms": pct(ttfts, 0.5),
                "ttft_p99_ms": pct(ttfts, 0.99),
                "itl_p50_ms": pct(itls, 0.5), "itl_p99_ms": pct(itls, 0.99),
                "wall_s": round(wall, 2),
                **({"admission": admission} if admission else {}),
                "outs": outs,
            }
            if best is not None:
                assert run["outs"] == best["outs"], \
                    "greedy outputs changed between repeats"
            if best is None or (run["goodput_tok_s"]
                                > best["goodput_tok_s"]):
                best = run
        del eng
        gc.collect()
        return best

    static_runs = [run_policy(b, c, adaptive=False) for b, c in statics]

    # 3. SELF-TUNE under the recompile sentinel's freeze: the adaptive
    # run must mint ZERO post-warmup keys (the ladder warmed them all)
    before = COMPILES.after_warmup
    prev_freeze = COMPILES.freeze
    COMPILES.freeze = True
    try:
        adaptive_run = run_policy(b_auto, chunk_max, adaptive=True)
    finally:
        COMPILES.freeze = prev_freeze
    compiles_after_warmup = COMPILES.after_warmup - before

    parity = all(run["outs"] == static_runs[0]["outs"]
                 for run in static_runs[1:] + [adaptive_run])
    for run in static_runs + [adaptive_run]:
        run.pop("outs")
    best_static = max(static_runs, key=lambda r: r["goodput_tok_s"])
    return {
        "metric": f"{prefix}_autotune_adaptive_goodput_tok_per_s_at_slo",
        "value": adaptive_run["goodput_tok_s"], "unit": "tok/s",
        "vs_baseline": None,
        "slo_ttft_ms": slo_ttft, "slo_itl_ms": slo_itl,
        "requests": n_req, "long_prompt_tokens": long_len,
        "serve_batch_auto": b_auto,
        "autosize": autosize,
        "calibration": {"batches": cal_batches,
                        "decode_curve": artifact["decode_curve"],
                        "prefill_ms_by_width":
                            artifact["prefill_ms_by_width"],
                        "knee": artifact["knee"],
                        "recommendation": artifact["recommendation"]},
        "adaptive": adaptive_run,
        "static": static_runs,
        "best_static": {k: best_static[k] for k in
                        ("batch", "chunk", "goodput_tok_s")},
        "vs_best_static": round(adaptive_run["goodput_tok_s"]
                                / best_static["goodput_tok_s"], 2)
        if best_static["goodput_tok_s"] else None,
        "beats_all_static": all(
            adaptive_run["goodput_tok_s"] >= r["goodput_tok_s"]
            for r in static_runs),
        "token_parity": parity,
        "compiles_after_warmup": compiles_after_warmup,
        "freeze_compiles": True,
    }


def _spec_row(prefix: str) -> dict:
    """REAL-draft speculative decoding (the ISSUE-13 metric): the
    zero-extra-weights truncated-depth self-draft (runtime/draft.py) vs
    prompt-lookup vs plain greedy, measured on a fixed-seed
    NON-REPETITIVE eval — the regime VERDICT #6 said the committed
    lookup rows never covered (their max-accept numbers were best-case
    by construction; this row carries the measured accept rate and a
    repetitiveness label ON the row so the regime is never implicit
    again).

    The model is synthetic with LAYER-DECAYED weights: the first
    `depth` layers carry scale `base`, deeper layers scale `tail` —
    the structural regime where a truncated-depth prefix predicts the
    full model (trained checkpoints approximate this late-layer
    redundancy; the accept rate REPORTED is what this construction
    measures, not a trained-model claim). The eval prompt is random
    tokens over a 2048 vocab and the greedy continuation is verified
    aperiodic (`repeated_3gram_frac`, `label`): prompt-lookup's own
    tokens/forward on the same stream is the honest control — on
    non-repetitive text it proposes nothing.

    Three single-stream passes (plain / lookup / self-draft, best-of-N
    wall each, bit-identical streams asserted) + one Poisson serving
    A/B: the same fixed arrival trace through the slot scheduler with
    per-slot drafts OFF then ON (token parity per request), with the
    compile ledger FROZEN after the draft-on warmup — the acceptance
    bars ride the row: `token_parity`, `value` > 1.5 (single-stream
    speedup), serving ratio > 1, `compiles_after_warmup` == 0.

    Env knobs: BENCH_SPEC_TOKENS (96), BENCH_SPEC_DEPTH (1),
    BENCH_SPEC_DRAFT_LEN (8), BENCH_SPEC_REQUESTS (12),
    BENCH_SPEC_BATCH (4), BENCH_SPEC_TAIL (0.05), BENCH_SPEC_REPEATS
    (= BENCH_REPEATS)."""
    import gc
    import time

    from distributed_llama_tpu.io import HostTensor
    from distributed_llama_tpu.io.model_file import model_tensor_plan
    from distributed_llama_tpu.models.params import load_params
    from distributed_llama_tpu.quants import FloatType
    from distributed_llama_tpu.runtime.draft import DraftModel, build_draft
    from distributed_llama_tpu.runtime.profiler import COMPILES
    from distributed_llama_tpu.runtime.scheduler import Scheduler
    from distributed_llama_tpu.sampler import Sampler

    n = int(os.environ.get("BENCH_SPEC_TOKENS", "96"))
    depth = int(os.environ.get("BENCH_SPEC_DEPTH", "1"))
    draft_len = int(os.environ.get("BENCH_SPEC_DRAFT_LEN", "8"))
    n_req = max(int(os.environ.get("BENCH_SPEC_REQUESTS", "12")), 4)
    b = int(os.environ.get("BENCH_SPEC_BATCH", "4"))
    tail = float(os.environ.get("BENCH_SPEC_TAIL", "0.05"))
    repeats = max(int(os.environ.get(
        "BENCH_SPEC_REPEATS", os.environ.get("BENCH_REPEATS", "2"))), 1)

    spec = ModelSpec(
        arch=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=8,
        n_heads=8, n_kv_heads=4, vocab_size=512, seq_len=512,
        hidden_act=HiddenAct.SILU, weights_float_type=FloatType.F32)
    rng = np.random.default_rng(0)
    host = {}
    for name, shape, _ft in model_tensor_plan(spec):
        if "rms" in name:
            x = 1.0 + rng.standard_normal(shape).astype(np.float32) * 0.02
        else:
            s = 0.35
            if name.startswith("layers."):
                if int(name.split(".")[1]) >= depth:
                    s = tail
            x = rng.standard_normal(shape).astype(np.float32) * s
        host[name] = HostTensor(name, FloatType.F32, shape, data=x)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)

    def engine(batch=1):
        return Engine(spec, params, compute_dtype=jnp.float32,
                      cache_dtype=jnp.float32, batch=batch,
                      prefill_chunk=64)

    def greedy():
        return Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=7)

    prompt = np.random.default_rng(123).integers(
        3, spec.vocab_size, 48).tolist()

    # -- single-stream ladder: plain / lookup / self-draft ----------------
    def timed(fn):
        best, toks = None, None
        for i in range(repeats + 1):  # run 0 compiles — excluded
            t0 = time.perf_counter()
            toks = fn()
            dt = time.perf_counter() - t0
            if i > 0:
                best = dt if best is None else min(best, dt)
        return best, toks

    eng_p = engine()

    def run_plain():
        eng_p.reset()
        return eng_p.generate(prompt, n, greedy()).tokens

    best_plain, plain_toks = timed(run_plain)

    eng_l = engine()

    def run_lookup():
        eng_l.reset()
        return eng_l.generate_lookup(prompt, n, draft_len=draft_len).tokens

    best_lk, lk_toks = timed(run_lookup)
    lk_fwd, lk_n = eng_l.last_accept_stats
    lk_spec = dict(eng_l.last_spec)

    eng_d = engine()
    draft = DraftModel.self_draft(eng_d, depth)

    def run_draft():
        eng_d.reset()
        return eng_d.generate_draft(prompt, n, draft=draft,
                                    draft_len=draft_len).tokens

    best_dr, dr_toks = timed(run_draft)
    dr_fwd, dr_n = eng_d.last_accept_stats
    dr_spec = dict(eng_d.last_spec)

    single_parity = plain_toks == lk_toks == dr_toks
    # repetitiveness label from the PLAIN stream's own n-gram statistics
    # (the honest regime marker — a 3-gram that recurs is exactly what
    # prompt-lookup mines)
    t_arr = np.asarray(plain_toks)
    seen: set = set()
    hits = 0
    for i in range(len(t_arr) - 2):
        g = tuple(t_arr[i:i + 3])
        hits += g in seen
        seen.add(g)
    rep_frac = hits / max(len(t_arr) - 2, 1)
    label = "repetitive" if rep_frac > 0.2 else "non_repetitive"

    # -- Poisson serving A/B: per-slot drafts OFF vs ON -------------------
    rng2 = np.random.default_rng(5)
    lens = [(8, 16, 32)[i % 3] for i in range(n_req)]
    prompts = [rng2.integers(3, spec.vocab_size, ln).tolist()
               for ln in lens]
    budget = 24
    # saturated offered load: ~3x the plain path's single-stream capacity
    mean_iat = (best_plain / n) * budget / max(b, 1) / 3.0
    arrivals = np.cumsum(rng2.exponential(mean_iat, n_req))

    def serve(drafting: bool):
        eng = engine(batch=b)
        sched = Scheduler(
            eng, chunk=16,
            draft_factory=(lambda e: build_draft(e, f"self:{depth}"))
            if drafting else None,
            draft_len=draft_len if drafting else 0,
            draft_vocab=spec.vocab_size)
        sched.warmup()
        frozen = before = None
        if drafting:
            # the sentinel proof: the whole speculative serve runs with
            # the ledger FROZEN — one unplanned key would abort the row
            before = COMPILES.after_warmup
            frozen, COMPILES.freeze = COMPILES.freeze, True
        try:
            sched.start()
            live = []
            t0 = time.perf_counter()
            for arr, p in zip(arrivals, prompts):
                dt = t0 + arr - time.perf_counter()
                if dt > 0:
                    time.sleep(dt)
                live.append(sched.submit(p, budget, greedy()))
            for r in live:
                assert r.finished.wait(600), "scheduler stalled"
            wall = time.perf_counter() - t0
        finally:
            if drafting:
                COMPILES.freeze = frozen
            sched.close()
        outs = []
        for r in live:
            toks = []
            for t in r.tokens(timeout=5):
                toks.append(t)
            outs.append(toks)
        extra = {}
        if drafting:
            extra = {"spec": sched.stats.spec.summary(),
                     "compiles_after_warmup": COMPILES.after_warmup
                     - before}
        del sched, eng
        gc.collect()
        return {"agg_tok_per_s": round(
            sum(len(o) for o in outs) / wall, 1), "outs": outs, **extra}

    off = serve(False)
    on = serve(True)
    serve_parity = off["outs"] == on["outs"]
    off.pop("outs")
    on.pop("outs")

    del eng_p, eng_l, eng_d, draft, params
    gc.collect()
    return {
        "metric": f"{prefix}_selfdraft_speculative_speedup_nonrepetitive",
        "value": round(best_plain / best_dr, 2), "unit": "x",
        "vs_baseline": None,
        "eval_label": label,
        "repeated_3gram_frac": round(rep_frac, 3),
        "tokens": n, "draft_depth": depth, "draft_len": draft_len,
        "token_parity": bool(single_parity and serve_parity),
        "selfdraft": {
            "tok_per_s": round(n / best_dr, 1),
            "tokens_per_forward": round(dr_n / dr_fwd, 2),
            "accept_rate": round(dr_spec["accepted"]
                                 / max(dr_spec["drafted"], 1), 3),
            "drafted": dr_spec["drafted"],
            "accepted": dr_spec["accepted"],
        },
        "prompt_lookup": {
            "tok_per_s": round(n / best_lk, 1),
            "speedup_vs_plain": round(best_plain / best_lk, 2),
            "tokens_per_forward": round(lk_n / lk_fwd, 2),
            "accept_rate": round(lk_spec["accepted"]
                                 / max(lk_spec["drafted"], 1), 3)
            if lk_spec["drafted"] else None,
            "drafted": lk_spec["drafted"],
        },
        "plain_tok_per_s": round(n / best_plain, 1),
        "serving_ab": {
            "requests": n_req, "batch": b, "budget": budget,
            "draft_off": off, "draft_on": on,
            "agg_speedup": round(on["agg_tok_per_s"]
                                 / off["agg_tok_per_s"], 2),
        },
        "compiles_after_warmup": on.get("compiles_after_warmup"),
    }


def _chaos_row(params, spec: ModelSpec, prefix: str, b: int = 4) -> dict:
    """Serving resilience under injected faults (the ISSUE-3 metric):
    replay a fixed-seed Poisson arrival trace through the SUPERVISED
    scheduler (runtime/resilience.EngineSupervisor) with deterministic
    step crashes injected mid-trace (runtime/faults.py), and report what a
    client fleet actually experiences:

      * availability %      — fraction of wall time /readyz would be 200
                              (polled at 5 ms)
      * recovered vs failed — requests that got a structured error frame
                              and succeeded on ONE client retry, vs ones
                              that did not
      * recovery p50 ms     — failure detected -> ready again
                              (SupervisorStats.recovery_ms)

    Env knobs: BENCH_CHAOS_REQUESTS (default 24), BENCH_CHAOS_BATCH
    (default 4), BENCH_CHAOS_CRASHES (default 2 — spaced across the
    trace: each next crash arms only after the previous recovery)."""
    import gc
    import threading
    import time

    from distributed_llama_tpu.runtime.faults import FAULTS
    from distributed_llama_tpu.runtime.resilience import EngineSupervisor
    from distributed_llama_tpu.runtime.scheduler import RequestError
    from distributed_llama_tpu.sampler import Sampler

    b = int(os.environ.get("BENCH_CHAOS_BATCH", str(b)))
    n_req = max(int(os.environ.get("BENCH_CHAOS_REQUESTS", "24")), 2)
    n_crashes = int(os.environ.get("BENCH_CHAOS_CRASHES", "2"))
    seq = min(512, spec.seq_len)
    cdt = jnp.float32 if jax.default_backend() == "cpu" else jnp.bfloat16

    rng = np.random.default_rng(0)
    lens = [(8, 16, 32)[i % 3] for i in range(n_req)]
    prompts = [rng.integers(1, spec.vocab_size, n).astype(np.int64).tolist()
               for n in lens]
    budgets = [int(x) for x in rng.integers(8, 33, n_req)]
    arrivals = np.cumsum(rng.exponential(0.05, n_req))

    def factory():
        return Engine(spec, params, compute_dtype=cdt, cache_dtype=cdt,
                      max_seq_len=seq, batch=b)

    sup = EngineSupervisor(factory, chunk=32, stall_timeout=60.0,
                           backoff_base=0.05, breaker_threshold=10_000)
    _note_hbm(sup.engine, sup.prefix_cache)

    def greedy():
        return Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=7)

    # availability sampler: what /readyz would answer, at 5 ms resolution
    ready_samples: list[bool] = []
    sampling = threading.Event()
    sampling.set()

    def sample_ready():
        while sampling.is_set():
            ready_samples.append(sup.ready)
            time.sleep(0.005)

    # crash scheduler: arm the next step crash only after the previous
    # recovery completed, so crashes SPACE OUT across the trace instead of
    # burning the breaker on back-to-back failures
    def inject_crashes():
        for k in range(n_crashes):
            while sup.sup_stats.recoveries < k and sampling.is_set():
                time.sleep(0.01)
            if not sampling.is_set():
                return
            FAULTS.arm("step_raise", after=5)  # a few steps of grace

    results = {"ok_first": 0, "recovered": 0, "unrecovered": 0}
    res_lock = threading.Lock()

    def run_request(prompt, budget):
        # one client-side retry: a structured error frame (RequestError)
        # or an unready rejection waits for /readyz then resubmits once
        for attempt in range(2):
            try:
                while not sup.ready:
                    time.sleep(0.02)
                req = sup.submit(prompt, budget, greedy())
                n = sum(1 for _ in req.tokens(timeout=120.0))
                with res_lock:
                    results["ok_first" if attempt == 0
                            else "recovered"] += 1
                return n
            except RequestError:
                if attempt == 1:
                    with res_lock:
                        results["unrecovered"] += 1
            except Exception:  # noqa: BLE001 — unready race on submit
                if attempt == 1:
                    with res_lock:
                        results["unrecovered"] += 1
        return 0

    threads: list[threading.Thread] = []
    tokens_out = [0] * n_req

    def client(i):
        tokens_out[i] = run_request(prompts[i], budgets[i])

    t0 = time.perf_counter()
    samp = threading.Thread(target=sample_ready, daemon=True)
    samp.start()
    inj = threading.Thread(target=inject_crashes, daemon=True)
    inj.start()
    try:
        for i in range(n_req):
            dt = t0 + arrivals[i] - time.perf_counter()
            if dt > 0:
                time.sleep(dt)
            t = threading.Thread(target=client, args=(i,), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=240.0)
    finally:
        sampling.clear()
        FAULTS.clear()
    wall = time.perf_counter() - t0
    samp.join(timeout=2.0)
    availability = (100.0 * sum(ready_samples) / len(ready_samples)
                    if ready_samples else 0.0)
    rec = sorted(sup.sup_stats.recovery_ms)
    rec_p50 = rec[len(rec) // 2] if rec else None
    summary = sup.summary()
    sup.close()
    del sup
    gc.collect()
    return {
        "metric": f"{prefix}_chaos_batch{b}_availability_pct",
        "value": round(availability, 2), "unit": "%", "vs_baseline": None,
        "requests": n_req,
        "crashes_injected": summary["resilience"]["crashes"],
        "ok_first_attempt": results["ok_first"],
        "recovered_by_retry": results["recovered"],
        "unrecovered": results["unrecovered"],
        "requests_failed_frames": summary["requests_failed"],
        "recoveries": summary["resilience"]["recoveries"],
        "recovery_p50_ms": round(rec_p50, 1) if rec_p50 is not None else None,
        "tokens_out": int(sum(tokens_out)),
        "wall_s": round(wall, 2),
    }


def _router_row(params, spec: ModelSpec, prefix: str, b: int = 2) -> dict:
    """Multi-replica serving tier (the ISSUE-6 metric): a shared-prefix
    Poisson trace — prompts drawn from BENCH_ROUTER_GROUPS distinct
    system-prompt families — served by TWO replicas twice:

      * ROUND_ROBIN — the "2x independent servers" regime: requests
        alternate replicas blindly, so every prefix family must warm on
        BOTH replicas before it ever hits;
      * CACHE_AWARE — the router's SGLang-style placement: each family
        concentrates on the replica whose radix tree already holds it,
        so a family pays exactly ONE cold prefill tier-wide.

    The placement A/B runs CLOSED-LOOP (one request in flight at a time):
    with a fixed seed the placement sequence — and therefore the
    hit/miss count — is fully DETERMINISTIC, so the reported gap
    measures the policy, never CPU timing luck. The chaos pass then
    re-serves the trace OPEN-LOOP (Poisson arrivals, work genuinely in
    flight) on cache_aware with ONE replica killed mid-trace
    (replica_raise, count-deterministic) to measure what clients
    experience during the failure: availability % (router readiness at
    5 ms), ZERO failed not-yet-streamed requests (retried on the
    survivor), structured frames for mid-stream ones, and greedy token
    parity with the crash-free runs.

    Env knobs: BENCH_ROUTER_REQUESTS (default 16), BENCH_ROUTER_BATCH
    (per-replica slots, default 2), BENCH_ROUTER_GROUPS (default 4),
    BENCH_ROUTER_SYS (shared tokens per family, default 48),
    BENCH_ROUTER_BLOCK (block_len, default 16), BENCH_ROUTER_BLOCKS
    (arena blocks per replica, default ample for every family),
    BENCH_ROUTER_TOKENS (decode budget, default 8),
    BENCH_ROUTER_KILL_AFTER (replica 0 steps before the kill, default 5).
    """
    import gc
    import threading
    import time

    from distributed_llama_tpu.runtime.faults import FAULTS
    from distributed_llama_tpu.runtime.router import Router
    from distributed_llama_tpu.runtime.scheduler import RequestError
    from distributed_llama_tpu.sampler import Sampler

    b = int(os.environ.get("BENCH_ROUTER_BATCH", str(b)))
    n_req = max(int(os.environ.get("BENCH_ROUTER_REQUESTS", "16")), 4)
    groups = max(int(os.environ.get("BENCH_ROUTER_GROUPS", "4")), 1)
    sys_len = int(os.environ.get("BENCH_ROUTER_SYS", "48"))
    bl = int(os.environ.get("BENCH_ROUTER_BLOCK", "16"))
    budget = int(os.environ.get("BENCH_ROUTER_TOKENS", "8"))
    kill_after = int(os.environ.get("BENCH_ROUTER_KILL_AFTER", "5"))
    blocks = int(os.environ.get(
        "BENCH_ROUTER_BLOCKS",
        str(2 * groups * (sys_len // bl + 1) + 8)))
    seq = min(512, spec.seq_len)
    cdt = jnp.float32 if jax.default_backend() == "cpu" else jnp.bfloat16

    rng = np.random.default_rng(0)
    families = [rng.integers(1, spec.vocab_size, sys_len).astype(
        np.int64).tolist() for _ in range(groups)]
    gidx = rng.integers(0, groups, n_req)
    tails = [rng.integers(1, spec.vocab_size, (8, 12, 16)[i % 3]).astype(
        np.int64).tolist() for i in range(n_req)]
    prompts = [families[int(gidx[i])] + tails[i] for i in range(n_req)]
    arrivals = np.cumsum(rng.exponential(0.04, n_req))

    def factory():
        return Engine(spec, params, compute_dtype=cdt, cache_dtype=cdt,
                      max_seq_len=seq, batch=b)

    def greedy():
        return Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=7)

    def run_trace(policy: str, kill: bool, closed_loop: bool) -> dict:
        FAULTS.clear()
        router = Router(factory, replicas=2, policy=policy, retry_budget=1,
                        chunk=bl, stall_timeout=60.0, backoff_base=0.05,
                        breaker_threshold=10_000, circuit_threshold=10_000,
                        prefix_blocks=blocks, prefix_block_len=bl)
        h0 = router.replicas[0]
        _note_hbm(h0.sup.engine, h0.sup.prefix_cache)  # one replica's
        # exact shape (siblings are identical and SHARE the weights)
        outs: dict = {}
        errs: dict = {}
        ready_samples: list = []
        sampling = threading.Event()
        sampling.set()

        def sample_ready():
            while sampling.is_set():
                ready_samples.append(router.ready)
                time.sleep(0.005)

        def client(i):
            got: list = []
            try:
                req = router.submit(prompts[i], budget, greedy())
                for t in req.tokens(timeout=300.0):
                    got.append(t)
                outs[i] = (got, req.retries)
            except RequestError as e:
                errs[i] = (len(got), e)
            except Exception as e:  # noqa: BLE001 — no-replica rejection
                errs[i] = (len(got), e)

        if kill:
            FAULTS.arm("replica_raise", key="r0", after=kill_after)
        samp = threading.Thread(target=sample_ready, daemon=True)
        samp.start()
        threads = []
        t0 = time.perf_counter()
        try:
            for i in range(n_req):
                if closed_loop:
                    # placement A/B: one request at a time — with both
                    # replicas idle at every pick, the placement (and so
                    # the hit count) is a pure, deterministic function
                    # of the policy
                    client(i)
                    continue
                dt = t0 + arrivals[i] - time.perf_counter()
                if dt > 0:
                    time.sleep(dt)
                t = threading.Thread(target=client, args=(i,), daemon=True)
                t.start()
                threads.append(t)
            for t in threads:
                t.join(timeout=300.0)
        finally:
            sampling.clear()
            FAULTS.clear()
        wall = time.perf_counter() - t0
        samp.join(timeout=2.0)
        # prefix-cache counters across EVERY generation of both replicas
        # (a killed replica's pre-crash stats live in its supervisor's
        # dead-generation list, not the rebuilt tree's fresh zeros)
        all_stats = []
        for h in router.replicas:
            all_stats.append(h.sup.stats)
            all_stats.extend(h.sup._dead_stats)
        lookups = sum(s.prefix.lookups for s in all_stats if s.prefix)
        hits = sum(s.prefix.hits for s in all_stats if s.prefix)
        saved = sum(s.prefix.tokens_saved for s in all_stats if s.prefix)
        prefilled = sum(s.prefix.tokens_prefilled for s in all_stats
                        if s.prefix)
        summary = router.summary()
        crashes = sum(r["resilience"]["crashes"]
                      for r in summary["replicas"])
        out = {
            "hit_rate_pct": round(100.0 * hits / lookups, 2) if lookups
            else 0.0,
            "prefill_saved_pct": round(
                100.0 * saved / (saved + prefilled), 2)
            if saved + prefilled else 0.0,
            "agg_tok_per_s": round(
                sum(len(o) for o, _ in outs.values()) / wall, 1),
            "ttft_p50_ms": summary["ttft_p50_ms"],
            "availability_pct": round(
                100.0 * sum(ready_samples) / len(ready_samples), 2)
            if ready_samples else None,
            "completed": len(outs),
            "unstreamed_failures": sum(1 for n, _ in errs.values()
                                       if n == 0),
            "midstream_failures": sum(1 for n, _ in errs.values()
                                      if n > 0),
            "retries": router.stats.retries,
            "failovers_ok": router.stats.failovers_ok,
            "crashes_injected": crashes,
            "outs": {i: o for i, (o, _) in outs.items()},
        }
        router.close()
        del router
        gc.collect()
        return out

    # three serves of the SAME trace: the placement A/B runs crash-free
    # (the hit-rate gap must measure the POLICY, not which run ate the
    # kill), then the chaos pass re-runs cache-aware with one replica
    # killed mid-trace for the availability/failover numbers
    rr = run_trace("round_robin", kill=False, closed_loop=True)
    ca = run_trace("cache_aware", kill=False, closed_loop=True)
    chaos = run_trace("cache_aware", kill=True, closed_loop=False)
    # greedy parity: every request COMPLETED in a run must match the
    # round-robin run token-for-token (failover replays are
    # bit-identical; mid-stream kills errored structurally and are
    # excluded by construction)
    parity = all(run["outs"][i] == rr["outs"][i]
                 for run in (ca, chaos) for i in run["outs"]
                 if i in rr["outs"])
    for run in (rr, ca, chaos):
        run.pop("outs")
    return {
        "metric": f"{prefix}_router_2rep_cache_aware_hit_rate_pct",
        "value": ca["hit_rate_pct"], "unit": "%", "vs_baseline": None,
        "requests": n_req, "replicas": 2, "batch_per_replica": b,
        "prefix_families": groups, "family_tokens": sys_len,
        "block_len": bl, "arena_blocks_per_replica": blocks,
        "token_parity": parity,
        "round_robin": rr, "cache_aware": ca, "cache_aware_chaos": chaos,
        "hit_rate_gain_pct": round(
            ca["hit_rate_pct"] - rr["hit_rate_pct"], 2),
    }


def _router_procs_row(prefix: str) -> dict:
    """Process-isolated replica tier (the ISSUE-7 metric): spawn TWO real
    replica worker OS processes (runtime/replica_worker.py — each its own
    single-process CPU-JAX interpreter over deterministic synthetic
    weights, served through the framed replica protocol), drive an
    open-loop Poisson trace through the failover router, and deliver a
    REAL ``SIGKILL -9`` to one worker mid-trace. Reported:

      * kill_to_routable_ms / respawn_p50_ms — death -> the respawned
        worker is warmed and routable again (the supervised-respawn
        bound the chaos tests pin);
      * availability_pct — router readiness sampled at 5 ms: the sibling
        replica must keep the SERVICE ready through the whole outage;
      * unstreamed_failures — requests that failed with zero tokens
        streamed: must be 0 (the connection EOF is a structured
        retryable frame, failed over to the sibling within the retry
        budget); mid-stream casualties get the structured non-retryable
        frame and are counted separately, never silently replayed;
      * token_parity — every completed serve of the same prompt (either
        replica, pre- or post-kill, failover replays, the respawned
        process) produced IDENTICAL greedy tokens. Compared pairwise
        across completions, so the bar is backend-independent: both
        workers hold bit-identical params by construction (same
        spec/seed), and the respawned one reloads exactly them.

    Workers pace decode via a worker-side ``slow_step`` fault so the kill
    provably lands while streams are in flight. Env knobs:
    BENCH_PROCS_REQUESTS (default 10), BENCH_PROCS_TOKENS (decode budget,
    default 6), BENCH_PROCS_KILL_AFTER (requests submitted before the
    kill, default half the trace), BENCH_PROCS_STEP_MS (decode pacing,
    default 40), BENCH_PROCS_SPAWN_TIMEOUT (startup/respawn bound,
    default 300 s — includes the worker's jax import + tiny-model
    compile on a cold XLA cache)."""
    import gc
    import signal as _signal
    import tempfile
    import threading
    import time as _time

    from distributed_llama_tpu.runtime.replica_worker import WorkerProc
    from distributed_llama_tpu.runtime.router import (RemoteReplicaHandle,
                                                      Router)
    from distributed_llama_tpu.runtime.scheduler import RequestError
    from distributed_llama_tpu.sampler import Sampler

    n_req = max(int(os.environ.get("BENCH_PROCS_REQUESTS", "10")), 4)
    budget = int(os.environ.get("BENCH_PROCS_TOKENS", "6"))
    kill_after = int(os.environ.get("BENCH_PROCS_KILL_AFTER",
                                    str(n_req // 2)))
    step_ms = int(os.environ.get("BENCH_PROCS_STEP_MS", "40"))
    spawn_timeout = float(os.environ.get("BENCH_PROCS_SPAWN_TIMEOUT",
                                         "300"))

    spec_fields = dict(dim=64, hidden_dim=128, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=128, seq_len=128)
    cfg = {"test_spec": spec_fields, "seed": 11, "scale": 0.05,
           "compute_dtype": "f32", "batch": 2,
           # the survivor absorbs the whole trace during the outage —
           # its admission queue must hold every not-yet-served request
           "serve": {"stall_timeout": 60.0, "max_queue": n_req},
           # worker-side flight recorder: each worker's step timeline
           # rides its stats reply (span events are off the hot path —
           # decode_every huge keeps the ring step-dominated)
           "trace": {"capacity": 2048, "decode_every": 1 << 30}}
    # workers are single-process CPU JAX regardless of the bench backend
    # (the process tier is host-side plumbing; the chip stays with the
    # parent's measured rows); they share one persistent XLA compilation
    # cache so worker 1 and every respawn reuse worker 0's compiles
    wenv = {"JAX_PLATFORMS": "cpu",
            "JAX_COMPILATION_CACHE_DIR": os.path.join(
                os.path.expanduser("~"), ".cache", "dllama_tpu_xla"),
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "1.0"}
    workdir = tempfile.mkdtemp(prefix="dllama-bench-procs-")

    def mk(i):
        proc = WorkerProc(i, dict(cfg, fault_key=f"r{i}"), workdir=workdir,
                          env=wenv,
                          faults=f"slow_step:times=0;ms={step_ms}")
        return RemoteReplicaHandle(i, proc=proc, poll_interval=0.1,
                                   spawn_backoff_base=0.05,
                                   spawn_timeout=spawn_timeout,
                                   respawn_timeout=spawn_timeout)

    # spawn the two worker processes CONCURRENTLY (handle construction
    # blocks on the port handshake — import + weight build + warmup):
    # the row measures kill-to-routable, not cold-start serialization
    handles: list = [None, None]
    builders = [threading.Thread(target=lambda i=i: handles.__setitem__(
        i, mk(i))) for i in (0, 1)]
    for t in builders:
        t.start()
    for t in builders:
        t.join()
    if any(h is None for h in handles):
        for h in handles:
            if h is not None:
                h.close()  # don't orphan the sibling that DID come up
        raise RuntimeError("replica worker spawn failed (see workdir logs)")

    rng = np.random.default_rng(3)
    # each distinct prompt appears (at least) twice in the trace — the
    # parity bar compares completions of the same prompt pairwise
    distinct = [rng.integers(1, spec_fields["vocab_size"],
                             12 + 4 * (i % 3)).astype(np.int64).tolist()
                for i in range(max(n_req // 2, 1))]
    prompts = [distinct[i % len(distinct)] for i in range(n_req)]
    arrivals = np.cumsum(rng.exponential(0.08, n_req))

    def greedy():
        return Sampler(spec_fields["vocab_size"], temperature=0.0,
                       topp=0.9, seed=5)

    router = Router(None, policy="round_robin", retry_budget=1,
                    handle_factories=[lambda: handles[0],
                                      lambda: handles[1]])
    h0 = router.replicas[0]
    outs: dict = {}
    errs: dict = {}
    ready_samples: list = []
    sampling = threading.Event()
    sampling.set()

    def sample_ready():
        while sampling.is_set():
            ready_samples.append(router.ready)
            _time.sleep(0.005)

    def client(i):
        got: list = []
        try:
            req = router.submit(prompts[i], budget, greedy())
            for t in req.tokens(timeout=300.0):
                got.append(t)
            outs[i] = got
        except RequestError as e:
            errs[i] = (len(got), e)
        except Exception as e:  # noqa: BLE001 — no-replica rejection
            errs[i] = (len(got), e)

    kill_to_routable_ms = None
    try:
        samp = threading.Thread(target=sample_ready, daemon=True)
        samp.start()
        threads = []
        t_kill = None
        t0 = _time.perf_counter()
        for i in range(n_req):
            dt = t0 + arrivals[i] - _time.perf_counter()
            if dt > 0:
                _time.sleep(dt)
            t = threading.Thread(target=client, args=(i,), daemon=True)
            t.start()
            threads.append(t)
            if i + 1 == kill_after:
                t_kill = _time.perf_counter()
                os.kill(h0._proc.proc.pid, _signal.SIGKILL)
        for t in threads:
            t.join(timeout=300.0)
        # supervised respawn: keep sampling readiness until the killed
        # replica is routable again (the acceptance bound)
        end = _time.perf_counter() + spawn_timeout
        while _time.perf_counter() < end and not h0.ready:
            _time.sleep(0.01)
        if h0.ready and t_kill is not None:
            kill_to_routable_ms = (_time.perf_counter() - t_kill) * 1e3
        # the respawned process SERVES: one more lap of the trace's first
        # two prompts so round_robin provably lands one on each replica
        for i in (0, 1):
            req = router.submit(prompts[i], budget, greedy())
            outs[n_req + i] = list(req.tokens(timeout=300.0))
            prompts.append(prompts[i])
    finally:
        sampling.clear()
        proc_stats = h0.proc_stats.summary()
        stats = router.stats
        # worker-local step timelines (steps never cross the boundary;
        # the stats reply carries each worker's summary) — keyed per
        # replica so two workers' compositions never merge
        step_timeline = {}
        hbm = {}
        for h in handles:
            s = (h.client.stats_summary() or {}) if h is not None else {}
            for k, v in (s.get("step_timeline") or {}).items():
                step_timeline[f"r{h.id}_{k}"] = v
            # per-WORKER hbm ledgers off the same stats reply (each
            # process owns its weights — no shared-buffer caveat here)
            if s.get("hbm"):
                hbm[f"r{h.id}"] = s["hbm"]
        router.close()
        gc.collect()

    by_prompt: dict = {}
    for i, toks in outs.items():
        by_prompt.setdefault(tuple(prompts[i]), []).append(toks)
    parity = all(all(o == serves[0] for o in serves)
                 for serves in by_prompt.values())
    return {
        "metric": f"{prefix}_router_procs_sigkill_respawn_ms",
        "value": (None if kill_to_routable_ms is None
                  else round(kill_to_routable_ms, 1)),
        "unit": "ms", "vs_baseline": None,
        "hbm": hbm,  # per-WORKER ledgers, rK-keyed (this row is emitted
        # outside _with_step_timeline — it builds its own blocks)
        "mode": "process", "replicas": 2, "requests": n_req,
        "decode_step_ms": step_ms,
        "kill_to_routable_ms": (None if kill_to_routable_ms is None
                                else round(kill_to_routable_ms, 1)),
        "respawn_p50_ms": proc_stats["respawn_p50_ms"],
        "respawns": proc_stats["respawns"],
        "exit_classes": proc_stats["exit_classes"],
        "availability_pct": round(
            100.0 * sum(ready_samples) / len(ready_samples), 2)
        if ready_samples else None,
        "completed": len(outs),
        "unstreamed_failures": sum(1 for n, _ in errs.values() if n == 0),
        "midstream_failures": sum(1 for n, _ in errs.values() if n > 0),
        "retries": stats.retries,
        "failovers_ok": stats.failovers_ok,
        "token_parity": parity,
        "step_timeline": step_timeline,
        # the acceptance bars ride the row
        "within_bound": (kill_to_routable_ms is not None
                         and kill_to_routable_ms / 1e3 < spawn_timeout),
        "spawn_timeout_s": spawn_timeout,
    }


def _fleet_row(prefix: str) -> dict:
    """Fleet-brain chaos row (the ISSUE-18 metric): TWO tenants drive a
    process-replica tier through a 10x Poisson load spike with one
    replica SIGKILLed mid-spike, under the FleetController
    (runtime/fleet.py). The victim tenant (high priority, weight 4)
    sends the SAME slow trickle before and during the spike; the hog
    tenant (low priority, weight 1, token-budgeted) floods 10x arrivals
    only during the spike. Reported bars:

      * victim_p99_ttft_ms — the victim's spike-phase p99 TTFT must
        stay at SLO (BENCH_FLEET_SLO_MS, default 2000): weighted-fair
        queueing means the hog's overage buys the hog latency, not the
        victim;
      * victim_p99_ratio — spike p99 over baseline p99 (reported; the
        fairness story in one number);
      * scale_ups >= 1 — the controller VISIBLY grew the replica set
        under the spike (pressure EWMA over threshold), HBM-capped;
      * unstreamed_failures == 0 — the SIGKILL mid-spike failed over
        every not-yet-streamed request; nothing was silently lost.

    Env knobs: BENCH_FLEET_REQUESTS (hog spike requests, default 16),
    BENCH_FLEET_VICTIM (victim requests per phase, default 6),
    BENCH_FLEET_TOKENS (decode budget, default 6), BENCH_FLEET_STEP_MS
    (worker decode pacing, default 40), BENCH_FLEET_SLO_MS (victim p99
    TTFT bar, default 2000), BENCH_FLEET_IAT (victim inter-arrival s,
    default 0.5; the hog floods at IAT/10), BENCH_FLEET_SPAWN_TIMEOUT
    (startup/scale-up bound, default 300 s)."""
    import gc
    import signal as _signal
    import tempfile
    import threading
    import time as _time

    from distributed_llama_tpu.runtime.fleet import (FleetConfig,
                                                     FleetController)
    from distributed_llama_tpu.runtime.replica_worker import WorkerProc
    from distributed_llama_tpu.runtime.router import (RemoteReplicaHandle,
                                                      Router)
    from distributed_llama_tpu.runtime.scheduler import RequestError
    from distributed_llama_tpu.sampler import Sampler

    n_hog = max(int(os.environ.get("BENCH_FLEET_REQUESTS", "16")), 4)
    n_victim = max(int(os.environ.get("BENCH_FLEET_VICTIM", "6")), 3)
    budget = int(os.environ.get("BENCH_FLEET_TOKENS", "6"))
    step_ms = int(os.environ.get("BENCH_FLEET_STEP_MS", "40"))
    slo_ms = float(os.environ.get("BENCH_FLEET_SLO_MS", "2000"))
    iat = float(os.environ.get("BENCH_FLEET_IAT", "0.5"))
    spawn_timeout = float(os.environ.get("BENCH_FLEET_SPAWN_TIMEOUT",
                                         "300"))

    spec_fields = dict(dim=64, hidden_dim=128, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=128, seq_len=128)
    cfg = {"test_spec": spec_fields, "seed": 11, "scale": 0.05,
           "compute_dtype": "f32", "batch": 2,
           # the whole spike may queue on two replicas while the third
           # spawns; weighted-fair ordering happens IN this queue
           "serve": {"stall_timeout": 60.0,
                     "max_queue": n_hog + 2 * n_victim,
                     # hog sustains 50 tok/s; the victim is unlimited —
                     # over budget, the hog is served only when no
                     # in-budget tenant waits
                     "tenant_budgets": "hog=1:50,victim=4"},
           "trace": {"capacity": 2048, "decode_every": 1 << 30}}
    wenv = {"JAX_PLATFORMS": "cpu",
            "JAX_COMPILATION_CACHE_DIR": os.path.join(
                os.path.expanduser("~"), ".cache", "dllama_tpu_xla"),
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "1.0"}
    workdir = tempfile.mkdtemp(prefix="dllama-bench-fleet-")

    def mk(i):
        proc = WorkerProc(i, dict(cfg, fault_key=f"r{i}"), workdir=workdir,
                          env=wenv,
                          faults=f"slow_step:times=0;ms={step_ms}")
        return RemoteReplicaHandle(i, proc=proc, poll_interval=0.1,
                                   spawn_backoff_base=0.05,
                                   spawn_timeout=spawn_timeout,
                                   respawn_timeout=spawn_timeout)

    handles: list = [None, None]
    builders = [threading.Thread(target=lambda i=i: handles.__setitem__(
        i, mk(i))) for i in (0, 1)]
    for t in builders:
        t.start()
    for t in builders:
        t.join()
    if any(h is None for h in handles):
        for h in handles:
            if h is not None:
                h.close()
        raise RuntimeError("replica worker spawn failed (see workdir logs)")

    router = Router(None, policy="round_robin", retry_budget=1,
                    handle_factories=[lambda: handles[0],
                                      lambda: handles[1]])
    # arm the scale-up path: the controller spawns r2.. through this
    router._spawn_factory = lambda rid, tier: mk(rid)
    fleet = FleetController(
        router, config=FleetConfig(min_replicas=2, max_replicas=3,
                                   poll=0.1, up_pressure=0.6,
                                   up_after=2, down_after=10_000,
                                   cooldown_ticks=2))
    h0 = router.replicas[0]
    rng = np.random.default_rng(7)
    prompt_of: dict = {}
    ttfts: dict = {}    # label -> ms
    errs: dict = {}

    def greedy():
        return Sampler(spec_fields["vocab_size"], temperature=0.0,
                       topp=0.9, seed=5)

    def client(label, tenant, priority, prompt):
        got: list = []
        t0 = _time.perf_counter()
        try:
            req = router.submit(prompt, budget, greedy(),
                                tenant=tenant, priority=priority)
            for t in req.tokens(timeout=300.0):
                if not got:
                    ttfts[label] = (_time.perf_counter() - t0) * 1e3
                got.append(t)
            prompt_of[label] = (tuple(prompt), tuple(got))
        except (RequestError, Exception) as e:  # noqa: BLE001
            errs[label] = (len(got), e)

    def run_phase(phase, victim_iat, hog_n, hog_iat, kill_at=None):
        threads = []
        v_arr = np.cumsum(rng.exponential(victim_iat, n_victim))
        h_arr = (np.cumsum(rng.exponential(hog_iat, hog_n))
                 if hog_n else np.array([]))
        events = sorted(
            [(t, "victim", i) for i, t in enumerate(v_arr)]
            + [(t, "hog", i) for i, t in enumerate(h_arr)])
        t0 = _time.perf_counter()
        for k, (at, who, i) in enumerate(events):
            dt = t0 + at - _time.perf_counter()
            if dt > 0:
                _time.sleep(dt)
            n_tok = 12 + 4 * (i % 3)
            prompt = rng.integers(1, spec_fields["vocab_size"],
                                  n_tok).astype(np.int64).tolist()
            pr = "high" if who == "victim" else "low"
            th = threading.Thread(target=client,
                                  args=(f"{phase}:{who}:{i}", who, pr,
                                        prompt), daemon=True)
            th.start()
            threads.append(th)
            if kill_at is not None and k + 1 == kill_at:
                os.kill(h0._proc.proc.pid, _signal.SIGKILL)
        for th in threads:
            th.join(timeout=300.0)

    try:
        # baseline: the victim alone, controller running but unprovoked
        fleet.start()
        run_phase("base", iat, 0, 0.0)
        base = sorted(v for k, v in ttfts.items() if k.startswith("base:"))
        # spike: hog floods at 10x the victim's rate; SIGKILL replica 0
        # a third of the way in — the controller must absorb BOTH
        run_phase("spike", iat, n_hog, iat / 10.0,
                  kill_at=max((n_hog + n_victim) // 3, 2))
        # let in-flight scale decisions land before reading the summary
        deadline = _time.perf_counter() + spawn_timeout
        while (_time.perf_counter() < deadline
               and router.scaling is not None):
            _time.sleep(0.05)
    finally:
        fleet_summary = fleet.summary()
        fleet.close()
        stats = router.stats
        router.close()
        gc.collect()

    spike = sorted(v for k, v in ttfts.items() if k.startswith("spike:")
                   and ":victim:" in k)
    base_p99 = base[int(0.99 * (len(base) - 1))] if base else None
    victim_p99 = spike[int(0.99 * (len(spike) - 1))] if spike else None
    # per-tenant view from the CLIENT side (the WFQ ledger itself lives
    # in the workers, where the queueing happens): completions + spike
    # p99 per tenant — the hog's queueing delay vs the victim's
    tenant_view = {}
    for who in ("victim", "hog"):
        lat = sorted(v for k, v in ttfts.items()
                     if k.startswith("spike:") and f":{who}:" in k)
        tenant_view[who] = {
            "completed": sum(1 for k in ttfts if f":{who}:" in k),
            "spike_p99_ttft_ms": (round(lat[int(0.99 * (len(lat) - 1))], 1)
                                  if lat else None),
        }
    # greedy parity across every completion of the same prompt length
    # is not meaningful here (prompts are unique); the parity bar lives
    # in the router/procs rows — this row pins fairness + scaling
    unstreamed = sum(1 for n, _ in errs.values() if n == 0)
    return {
        "metric": f"{prefix}_fleet_spike_victim_p99_ttft_ms",
        "value": (None if victim_p99 is None else round(victim_p99, 1)),
        "unit": "ms", "vs_baseline": None,
        "mode": "process", "boot_replicas": 2,
        "hog_requests": n_hog, "victim_requests_per_phase": n_victim,
        "decode_step_ms": step_ms, "slo_ms": slo_ms,
        "victim_base_p99_ttft_ms": (None if base_p99 is None
                                    else round(base_p99, 1)),
        "victim_p99_ratio": (None if not (base_p99 and victim_p99)
                             else round(victim_p99 / base_p99, 2)),
        "victim_within_slo": (victim_p99 is not None
                              and victim_p99 <= slo_ms),
        "scale_ups": fleet_summary.get("scale_ups", 0),
        "scale_blocked_hbm": fleet_summary.get("scale_blocked_hbm", 0),
        "actual_replicas_end": fleet_summary.get("actual_replicas"),
        "tenants": tenant_view,
        "completed": len(ttfts),
        "unstreamed_failures": unstreamed,
        "midstream_failures": sum(1 for n, _ in errs.values() if n > 0),
        "retries": stats.retries, "failovers_ok": stats.failovers_ok,
        # the acceptance bars ride the row
        "within_bound": (victim_p99 is not None and victim_p99 <= slo_ms
                         and unstreamed == 0
                         and fleet_summary.get("scale_ups", 0) >= 1),
    }


def _cluster_chaos_row(prefix: str) -> dict:
    """Cluster worker-loss detection latency (the ISSUE-5 metric): spawn
    REAL two-OS-process control-plane clusters (parallel/cluster_harness
    .py — no model/mesh, pure root<->worker star) and measure
    death-of-worker -> root's structured ClusterPeerLost, wall clock,
    for the two failure shapes:

      * detect_eof_ms   — worker os._exit mid-phase (socket EOF: the
                          fast path), p50 over BENCH_CLUSTER_REPEATS runs
      * detect_stall_ms — worker reader wedged via the recv_stall fault
                          (socket stays open; only heartbeat silence can
                          see it): must land within worker_timeout + one
                          recv granularity, never hang

    Env knobs: BENCH_CLUSTER_REPEATS (default 3), BENCH_CLUSTER_TIMEOUT
    (--worker-timeout, default 2.0), BENCH_CLUSTER_HB (default 0.2)."""
    import time as _time

    from distributed_llama_tpu.testing import free_port

    repeats = int(os.environ.get("BENCH_CLUSTER_REPEATS", "3"))
    w_timeout = float(os.environ.get("BENCH_CLUSTER_TIMEOUT", "2.0"))
    hb = float(os.environ.get("BENCH_CLUSTER_HB", "0.2"))
    harness = "distributed_llama_tpu.parallel.cluster_harness"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # the harness never inits a backend
    env.pop("DLLAMA_FAULTS", None)

    def launch_pair(phases, worker_extra=(), faults=""):
        """ONE home for the harness launch/parse/reap protocol (fault
        and clean runs both ride it — a CLI/framing change must not be
        made twice). Returns (root events, worker events); a worker
        whose reader is wedged by a fault never exits on its own and is
        reaped before its communicate."""
        port = free_port()
        wenv = dict(env)
        if faults:
            wenv["DLLAMA_FAULTS"] = faults
        common = ["--heartbeat-interval", str(hb),
                  "--worker-timeout", str(w_timeout)]
        root = subprocess.Popen(
            [sys.executable, "-m", harness, "root", "--port", str(port),
             "--phases", phases, *common],
            env=env, text=True, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL)
        worker = subprocess.Popen(
            [sys.executable, "-m", harness, "worker", "--port", str(port),
             "--rank", "1", *common, *worker_extra],
            env=wenv, text=True, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL)
        try:
            r_out, _ = root.communicate(timeout=w_timeout + 90)
            if worker.poll() is None:  # wedged reader never exits on its
                worker.kill()          # own — reap it immediately
            w_out, _ = worker.communicate(timeout=10)
            return ([json.loads(ln) for ln in r_out.splitlines()
                     if ln.startswith("{")],
                    [json.loads(ln) for ln in w_out.splitlines()
                     if ln.startswith("{")])
        finally:
            for p in (root, worker):
                if p.poll() is None:
                    p.kill()
                    p.communicate(timeout=10)

    def run_pair(worker_extra, faults=""):
        r_ev, w_ev = launch_pair("formation:0.1,decode:60",
                                 worker_extra, faults)
        lost = next(e for e in r_ev if e["event"] == "cluster_peer_lost")
        return lost, w_ev

    def clean_pair(phases: str):
        """One CLEAN run (no faults, normal shutdown): the wire-ledger
        source. Returns (root complete.stats, worker shutdown.stats,
        [tick phase names])."""
        r_ev, w_ev = launch_pair(phases)
        return (next(e for e in r_ev if e["event"] == "complete")["stats"],
                next(e for e in w_ev if e["event"] == "shutdown")["stats"],
                [e["phase"] for e in w_ev if e["event"] == "tick"])

    eof_ms = []
    for _ in range(repeats):
        lost, w_ev = run_pair(["--die-after", "0.5"])
        died = next(e for e in w_ev if e["event"] == "dying")
        eof_ms.append((lost["t_wall"] - died["t_wall"]) * 1e3)
    # one stall run: detection latency ~= worker_timeout by construction,
    # measured from the worker's LAST frame (the root's own accounting).
    # Monotonic clock for the local interval — an NTP step mid-run would
    # corrupt a wall-clock difference (the cross-process t_wall deltas
    # above are the one place wall clock is unavoidable)
    t0 = _time.perf_counter()
    lost, _ = run_pair([], faults="recv_stall:after=2;times=0")
    stall_wall_s = _time.perf_counter() - t0
    eof_ms.sort()

    # the measured wire plane (dlwire): one clean run's ledger from both
    # ends, reconciled EXACTLY against frame-size arithmetic — the
    # protocol frames (phase ticks) have deterministic sizes, so drift
    # here is 0 by construction or the ledger is broken
    from distributed_llama_tpu.parallel.multihost import (_HEADER_LEN,
                                                          frame_bytes)
    from distributed_llama_tpu.runtime.netstats import reconcile_wire
    phases = "formation:0.1,tick_a:0.3,tick_b:0.3"
    root_stats, worker_stats, ticks = clean_pair(phases)
    w_peer0 = ((worker_stats.get("wire") or {}).get("peers") or {}
               ).get("0") or {}
    measured_run_rx = ((w_peer0.get("rx") or {}).get("RUN")
                       or {"bytes": 0})["bytes"]
    modeled_run_rx = sum(frame_bytes(_HEADER_LEN, len(name.encode()))
                         for name in ticks)
    reconcile = reconcile_wire(measured_run_rx, modeled_run_rx,
                               unit="bytes")
    # the row's step_timeline: the control plane's "step" is one
    # heartbeat round trip — every RTT sample from the clean run's
    # ledger feeds the dec0/pre0/c0 composition (decode-curve consumers
    # ignore dec=0 rows by construction; dlprof's wire report reads it)
    wire = root_stats.get("wire") or {}
    for peer_rec in (wire.get("peers") or {}).values():
        for rtt in (peer_rec.get("rtt_ms") or {}).get("recent", ()):
            TRACER.step(decode_rows=0, prefill_rows=0, chunk=0,
                        queue_depth=0, wall_ms=rtt)
    return {
        "metric": f"{prefix}_cluster_detect_eof_ms",
        "value": round(eof_ms[len(eof_ms) // 2], 1), "unit": "ms",
        "vs_baseline": None,
        "repeats": repeats,
        "detect_eof_ms_all": [round(v, 1) for v in eof_ms],
        "detect_stall_last_seen_s": lost["last_seen_s"],
        "stall_run_wall_s": round(stall_wall_s, 2),
        "worker_timeout_s": w_timeout,
        "heartbeat_interval_s": hb,
        "stall_reason": lost["reason"],
        # the acceptance bar rides the row: detection is bounded
        "within_bound": (eof_ms[-1] / 1e3 < w_timeout
                         and lost["last_seen_s"] < w_timeout + 1.0),
        # the measured cluster wire plane (root + worker ledgers of the
        # clean run) and the exact control-plane reconciliation
        "wire": {"root": wire, "worker": worker_stats.get("wire") or {},
                 "reconcile": reconcile},
    }


def _variant_rows(engine, params, spec: ModelSpec, repeats: int, emit) -> None:
    """Extra measured rows for the default 7b run: prefill throughput,
    8k-fill long-context decode (bf16 and fp8 caches — the documented fp8
    attention tax as a measured artifact), and the lookup-decode row.
    Each row is passed to `emit` the moment it is measured."""
    import gc

    n_pre = 2048
    # prefill runs are short (~0.4 s) and tunnel jitter is ±30%: extra
    # repeats are nearly free and tighten the best-of-N
    tok_s = _measure_prefill(engine, n_pre, max(repeats, 4))
    emit({
        "metric": "llama2_7b_q40_prefill_2048_tok_per_s",
        "value": round(tok_s, 1), "unit": "tok/s", "vs_baseline": None,
        "step_timeline": {}})

    spec8k = dataclasses.replace(spec, seq_len=8192)
    for cdt, name in ((jnp.bfloat16, "bf16"), (jnp.float8_e4m3fn, "f8")):
        eng = Engine(spec8k, params, compute_dtype=jnp.bfloat16,
                     cache_dtype=cdt, max_seq_len=8192)
        emit(_with_step_timeline(
            lambda eng=eng, cdt=cdt, name=name: _decode_row(
                f"llama2_7b_q40_decode_8kfill_{name}_cache_ms_per_token",
                spec8k, _measure_decode(eng, 256, 7680, repeats),
                fill=7680, n_tokens=256,
                cache_itemsize=jnp.dtype(cdt).itemsize)))
        del eng
        gc.collect()

    emit(_with_step_timeline(_shardmap_row, engine, params, spec, repeats))
    emit(_with_step_timeline(_lookup_row, engine, repeats))
    # batched decode needs its own engine (batch is a build-time shape);
    # the 7b weights are shared, the extra KV cache is 512-seq x 8 rows
    emit(_with_step_timeline(_batch_row, params, spec, repeats))
    emit(_with_step_timeline(_batch_lookup_row, params, spec, repeats))


def _shardmap_row(engine, params, spec: ModelSpec, repeats: int) -> dict:
    """The multi-chip kernel path ON SILICON (VERDICT r4 #1): a 1-device
    Mesh(('tp',)) engine with force_mesh_kernels=True runs every Q40 matmul
    and the flash attention as Pallas kernels INSIDE shard_map manual
    regions — the exact lowering (Mosaic under manual partitioning) that
    every multi-chip perf claim rides on, previously executed only in
    interpret mode off-chip. Measured INTERLEAVED against the direct-kernel
    engine (tunnel jitter is ±30%; same-process alternation, best-of-N per
    variant) and reported as a parity ratio."""
    from distributed_llama_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(tp=1, devices=jax.devices()[:1])
    eng_sm = Engine(spec, params, mesh, compute_dtype=jnp.bfloat16,
                    cache_dtype=jnp.bfloat16, max_seq_len=spec.seq_len,
                    force_mesh_kernels=True)
    n = 128
    best_direct = best_sm = None
    for _ in range(max(repeats, 3)):
        ms_d = _measure_decode(engine, n, 0, 1)
        ms_s = _measure_decode(eng_sm, n, 0, 1)
        best_direct = ms_d if best_direct is None else min(best_direct, ms_d)
        best_sm = ms_s if best_sm is None else min(best_sm, ms_s)
    row = _decode_row("llama2_7b_q40_decode_shardmap_1dev_ms_per_token",
                      spec, best_sm, n_tokens=n)
    row["direct_ms_per_token"] = round(best_direct, 3)
    row["vs_direct_kernel"] = round(best_sm / best_direct, 3)
    del eng_sm
    import gc

    gc.collect()  # engines hold reference cycles; free the HBM now
    return row


def _moe_row(repeats: int) -> dict:
    """Mixtral-shaped MoE decode (the expert-gather path,
    ops/pallas_q40.q40_expert_matmul). Runs with the chip to itself —
    callers must drop the 7b engine/params first (a resident 3.9 GB
    neighbor measured ~25% off the standalone bandwidth)."""
    import gc

    moe_params = synth_q40_params(MIXTRAL_MOE)
    eng = Engine(MIXTRAL_MOE, moe_params, compute_dtype=jnp.bfloat16,
                 cache_dtype=jnp.bfloat16)
    msm = _measure_decode(eng, 256, 0, repeats)
    row = _decode_row("mixtral_moe_q40_decode_ms_per_token_1chip",
                      MIXTRAL_MOE, msm, n_tokens=256)
    # per-layer cost extrapolates to full-depth Mixtral/Grok (decode cost is
    # layer-linear; wcls/embedding amortize further at 32 layers)
    row["ms_per_token_per_layer"] = round(msm / MIXTRAL_MOE.n_layers, 4)
    del eng, moe_params
    gc.collect()
    return row


def _grok_row(repeats: int) -> dict:
    """Grok-1 decode at PRODUCTION widths (VERDICT r4 #5): the 4-norm GELU
    MoE block at dim 6144 / hidden 32768 / 131k vocab, 2 layers resident
    (7.6 GB — a full-width layer is 2.72 GB packed). Needs the chip alone
    like _moe_row; the per-layer column extrapolates to all 64 layers."""
    import gc

    params = synth_q40_params(GROK1_TRUNC)
    eng = Engine(GROK1_TRUNC, params, compute_dtype=jnp.bfloat16,
                 cache_dtype=jnp.bfloat16)
    msg = _measure_decode(eng, 128, 0, repeats)
    row = _decode_row("grok1_fullwidth_q40_decode_ms_per_token_1chip",
                      GROK1_TRUNC, msg, n_tokens=128)
    row["ms_per_token_per_layer"] = round(msg / GROK1_TRUNC.n_layers, 4)
    row["full_depth_64l_ms_per_token_extrapolated"] = round(
        msg / GROK1_TRUNC.n_layers * 64, 2)
    del eng, params
    gc.collect()
    return row


def _kvx_row(params, spec: ModelSpec, prefix: str) -> dict:
    """Cross-replica KV block transfer row (the ISSUE-14 metric,
    runtime/kv_transfer.py), two passes:

    1. COLD-REPLICA FILL A/B — a shared-prefix Poisson-paced trace of
       family pairs served by a 2-replica router (in-process
       ReplicaServers behind connect-mode handles: every frame crosses
       a REAL socket) under round-robin placement, so each family's
       second request lands on the replica that has NEVER seen it.
       Transfer OFF: the cold replica re-prefills the family prefix.
       Transfer ON: it fetches the donor's published blocks
       (RMSG_BLOCK_*) and prefills only the tail. Reported: cold-request
       TTFT p50 OFF vs ON (acceptance: >= 30% better ON), fill hit
       rate, measured BLOCK_DATA wire bytes RECONCILED against the
       frame-size arithmetic (25% bar; exact by construction), greedy
       TOKEN PARITY between the runs, and zero post-warmup compiles
       with the ledger FROZEN through the ON serve.

    2. DISAGGREGATED PREFILL/DECODE A/B — a decode-heavy stream with
       long prompts arriving concurrently, served by (a) ONE unified
       mixed replica and (b) a prefill-tier + decode-tier pair (equal
       decode capacity). Reported: the decode stream's ITL p99 + the
       long prompts' TTFT p50 under both shapes, parity + zero
       failures asserted (the perf delta is the finding, CPU timing is
       not asserted).

    Env knobs: BENCH_KVX_FAMILIES (6), BENCH_KVX_SYS (64),
    BENCH_KVX_BLOCK (16), BENCH_KVX_TOKENS (8), BENCH_KVX_IAT (0.02),
    BENCH_KVX_LONG (96), BENCH_KVX_STREAMS (4)."""
    import gc
    import time

    from distributed_llama_tpu.parallel.multihost import frame_bytes
    from distributed_llama_tpu.runtime import kv_transfer as kvx
    from distributed_llama_tpu.runtime.engine import Engine as _Eng
    from distributed_llama_tpu.runtime.netstats import (
        estimate_block_transfer, reconcile_wire)
    from distributed_llama_tpu.runtime.profiler import COMPILES
    from distributed_llama_tpu.runtime.replica_worker import ReplicaServer
    from distributed_llama_tpu.runtime.resilience import EngineSupervisor
    from distributed_llama_tpu.runtime.router import (RemoteReplicaHandle,
                                                      Router)
    from distributed_llama_tpu.sampler import Sampler

    n_fam = int(os.environ.get("BENCH_KVX_FAMILIES", "6"))
    sys_len = int(os.environ.get("BENCH_KVX_SYS", "64"))
    bl = int(os.environ.get("BENCH_KVX_BLOCK", "16"))
    budget = int(os.environ.get("BENCH_KVX_TOKENS", "8"))
    iat = float(os.environ.get("BENCH_KVX_IAT", "0.02"))
    long_len = int(os.environ.get("BENCH_KVX_LONG", "96"))
    n_streams = int(os.environ.get("BENCH_KVX_STREAMS", "4"))
    seq = min(512, spec.seq_len)
    cdt = jnp.float32 if jax.default_backend() == "cpu" else jnp.bfloat16
    b = 2

    def sup_factory(key=None):
        def make_engine():
            return _Eng(spec, params, batch=b, compute_dtype=cdt,
                        cache_dtype=cdt, max_seq_len=seq)
        # chunk = block_len, like the prefix row: the A/B measures
        # chunked prefill vs block fills — a chunk wider than the whole
        # prompt would hide the prefill cost inside one fixed-width
        # forward and measure nothing
        return lambda: EngineSupervisor(
            make_engine, chunk=bl,
            prefix_blocks=max(2 * b * seq // bl, 64),
            prefix_block_len=bl, kv_transfer=True, stall_timeout=60.0,
            fault_key=key)

    def cluster(tiers, *, transfer, policy="round_robin"):
        servers = [ReplicaServer(sup_factory(f"r{i}"),
                                 kv_transfer=transfer, tier=t)
                   for i, t in enumerate(tiers)]
        ports = [s.start() for s in servers]
        handles = [RemoteReplicaHandle(i, address=("127.0.0.1", p),
                                       block_len=bl, poll_interval=0.1)
                   for i, p in enumerate(ports)]
        router = Router(None, policy=policy,
                        handle_factories=[(lambda h=h: h)
                                          for h in handles],
                        kv_transfer=transfer, fill_min_tokens=bl)
        return servers, handles, router

    def greedy():
        return Sampler(spec.vocab_size, temperature=0.0, topp=0.9,
                       seed=7)

    rng = np.random.default_rng(0)
    fams = [rng.integers(1, spec.vocab_size, sys_len).astype(
        np.int64).tolist() for _ in range(n_fam)]
    tails = [rng.integers(1, spec.vocab_size, 4 + (i % 3) * 4).astype(
        np.int64).tolist() for i in range(n_fam)]
    gaps = rng.exponential(iat, n_fam)

    def run_fill_trace(transfer):
        """Pairs per family: the warm request places on r0 (round robin)
        and publishes; the cold one places on r1. Returns (tokens,
        cold-TTFT list, servers) — servers still open for ledger reads."""
        servers, _handles, router = cluster(("mixed", "mixed"),
                                            transfer=transfer)
        if transfer:
            COMPILES.freeze = True  # acceptance: the ON serve mints
            # zero post-warmup keys (a violation fails requests loudly)
        outs, cold_ttft = [], []
        try:
            for i, (fam, tail) in enumerate(zip(fams, tails)):
                time.sleep(min(gaps[i], 0.2))
                prompt = fam + tail
                warm = router.submit(prompt, budget, greedy())
                outs.append(list(warm.tokens(timeout=120)))
                cold = router.submit(prompt, budget, greedy())
                outs.append(list(cold.tokens(timeout=120)))
                assert cold.replica_id != warm.replica_id
                cold_ttft.append(cold.stats.ttft_ms)
        finally:
            COMPILES.freeze = False
        summary = router.summary()
        router.close()
        return outs, sorted(cold_ttft), servers, summary

    warm_compiles = COMPILES.after_warmup
    outs_off, ttft_off, servers_off, _ = run_fill_trace(False)
    for s in servers_off:
        s.shutdown()
    outs_on, ttft_on, servers_on, summ_on = run_fill_trace(True)
    frozen_delta = COMPILES.after_warmup - warm_compiles

    # the measured block-frame ledger vs the exact frame arithmetic
    agg = summ_on["kv_transfer"]
    measured_data = sum(
        srv.kvx_stats.wire.peer_bytes(peer, "BLOCK_DATA", "rx")
        for srv in servers_on for peer in (0, 1))
    per_block = kvx.block_payload_bytes(
        spec.n_layers, spec.n_kv_heads, bl, spec.head_size, cdt)
    modeled_data = agg["blocks_filled"] * frame_bytes(1, per_block)
    rec = reconcile_wire(measured_data, modeled_data)
    est = estimate_block_transfer(
        spec, tokens=agg["blocks_filled"] * bl, block_len=bl,
        cache_bytes=jnp.dtype(cdt).itemsize)
    for s in servers_on:
        s.shutdown()

    # -- pass 2: disaggregated prefill/decode A/B ------------------------
    longs = [rng.integers(1, spec.vocab_size, long_len).astype(
        np.int64).tolist() for _ in range(n_streams)]
    shorts = [rng.integers(1, spec.vocab_size, 8).astype(
        np.int64).tolist() for _ in range(n_streams)]

    def run_disagg(tiers, transfer):
        servers, _h, router = cluster(tiers, transfer=transfer)
        outs, itls, ttfts = [], [], []
        try:
            import threading as _th
            results = {}

            def serve(tag, prompt, toks):
                r = router.submit(prompt, toks, greedy())
                results[tag] = (list(r.tokens(timeout=180)), r.stats)

            threads = []
            for i in range(n_streams):
                threads.append(_th.Thread(
                    target=serve, args=(f"s{i}", shorts[i], 24)))
                threads.append(_th.Thread(
                    target=serve, args=(f"l{i}", longs[i], 4)))
            for t in threads:
                t.start()
                time.sleep(iat)
            for t in threads:
                t.join(timeout=240)
            for i in range(n_streams):
                toks, st = results[f"s{i}"]
                outs.append(toks)
                if st.itl_ms is not None:
                    itls.append(st.itl_ms)
                toks_l, st_l = results[f"l{i}"]
                outs.append(toks_l)
                ttfts.append(st_l.ttft_ms)
        finally:
            router.close()
            for s in servers:
                s.shutdown()
        itls.sort()
        ttfts.sort()
        return outs, {
            "itl_p99_ms": round(itls[-1], 3) if itls else None,
            "itl_p50_ms": round(itls[len(itls) // 2], 3)
            if itls else None,
            "long_ttft_p50_ms": round(ttfts[len(ttfts) // 2], 3)
            if ttfts else None,
        }

    outs_uni, uni = run_disagg(("mixed",), transfer=False)
    outs_dis, dis = run_disagg(("prefill", "decode"), transfer=True)

    gc.collect()
    ttft_off_p50 = ttft_off[len(ttft_off) // 2]
    ttft_on_p50 = ttft_on[len(ttft_on) // 2]
    gain = (ttft_off_p50 - ttft_on_p50) / ttft_off_p50 \
        if ttft_off_p50 else 0.0
    return {
        "metric": f"{prefix}_kv_transfer_cold_ttft_gain_pct",
        "value": round(100.0 * gain, 2),
        "unit": "%", "vs_baseline": None,
        "families": n_fam, "shared_prefix_tokens": sys_len,
        "block_len": bl,
        "token_parity": outs_on == outs_off,
        "token_parity_disagg": outs_dis == outs_uni,
        "cold_ttft_p50_ms_off": round(ttft_off_p50, 3),
        "cold_ttft_p50_ms_on": round(ttft_on_p50, 3),
        "fill_hit_rate": (round(agg["fills_ok"]
                                / agg["fills_requested"], 4)
                          if agg["fills_requested"] else None),
        "fills_ok": agg["fills_ok"],
        "fill_fallbacks": agg["fill_fallbacks"],
        "tokens_filled": agg["tokens_filled"],
        "blocks_filled": agg["blocks_filled"],
        "bytes_rx": agg["bytes_rx"],
        "compiles_after_warmup": frozen_delta,
        "unified": uni, "disaggregated": dis,
        "kv_transfer": {**agg, "reconcile": rec},
        "wire_model": est,
        "reconcile": rec,
    }


def _vocab_child() -> None:
    """Child body of the BENCH_VOCAB row (own process: the vocab A/B
    needs a tp mesh, and the virtual-device XLA flag is parse-once per
    process). Serves the SAME mixed greedy/sampled trace through a real
    Scheduler on a tp mesh twice — vocab-sharded vs replicated head —
    asserting greedy token parity, then times the head+sample path of
    one decode step per variant and reads both HBM ledgers. Prints ONE
    JSON line on stdout."""
    import gc
    import time

    from distributed_llama_tpu.parallel.mesh import make_mesh
    from distributed_llama_tpu.runtime.profiler import COMPILES, hbm_ledger
    from distributed_llama_tpu.runtime.scheduler import Scheduler
    from distributed_llama_tpu.sampler import Sampler

    tp = int(os.environ.get("BENCH_VOCAB_TP", "2"))
    b = int(os.environ.get("BENCH_VOCAB_BATCH", "2"))
    n_req = max(int(os.environ.get("BENCH_VOCAB_REQUESTS", "8")), 2)
    budget = int(os.environ.get("BENCH_VOCAB_TOKENS", "8"))
    steps = int(os.environ.get("BENCH_VOCAB_STEPS", "30"))
    spec = TINY
    params = synth_q40_params(spec)

    def serve(shard: bool):
        mesh = make_mesh(tp=tp, dp=1)
        eng = Engine(spec, dict(params), mesh, batch=b,
                     compute_dtype=jnp.bfloat16, cache_dtype=jnp.bfloat16,
                     max_seq_len=spec.seq_len, shard_vocab=shard)
        sched = Scheduler(eng, chunk=32)
        sched.warmup()
        COMPILES.reset()
        eng.mark_compile_warm()  # frozen-ledger bar: serving the trace
        COMPILES.freeze = True   # must mint ZERO new keys per variant
        outs = []
        try:
            reqs = []
            for i in range(n_req):
                # even requests greedy (parity bar), odd sampled at a
                # fixed seed (the sharded candidate path must serve them)
                temp = 0.0 if i % 2 == 0 else 0.8
                smp = Sampler(spec.vocab_size, temp, 0.9, seed=1234 + i,
                              backend="python")
                reqs.append(sched.submit(
                    [1 + i % 7, 5, 9 + i % 3, 2], budget, smp))
            while sched.has_work():
                sched.step()
            outs = [list(r.tokens()) for r in reqs]
            # parity bar = GREEDY rows only (even indices): sampled rows
            # are distribution-exact but their candidate probabilities
            # are the DEVICE softmax — a 1-ulp difference vs the host
            # softmax near a crossing could legitimately flip a sampled
            # token, and the design never promises sampled bit-parity
            greedy_outs = outs[0::2]
            frozen_delta = COMPILES.after_warmup
            # head+sample wall: one gated decode dispatch + the host
            # sample path (full (B, V) fetch vs sharded summaries)
            gate = np.full((b,), eng.seq_len, np.int32)
            tokz = np.zeros((b, 1), np.int32)
            view_vocab = spec.vocab_size
            smp_t = Sampler(spec.vocab_size, 0.0, 0.9, seed=7,
                            backend="python")
            best = None
            for _ in range(max(steps, 3)):
                t0 = time.perf_counter()
                lg = eng.slot_decode_step(tokz, gate)
                view = eng.sample_view(lg, None, view_vocab)
                view.sample(smp_t, 0)
                dt = (time.perf_counter() - t0) * 1e3
                best = dt if best is None else min(best, dt)
            led = hbm_ledger(eng, device_stats=False)
        finally:
            COMPILES.freeze = False
            sched.close()
        stats = dict(getattr(eng, "vocab_sample_stats", {}))
        del eng, sched
        gc.collect()
        return greedy_outs, outs, best, led, frozen_delta, stats

    g_on, outs_on, head_on, led_on, froz_on, st_on = serve(True)
    g_off, outs_off, head_off, led_off, froz_off, _ = serve(False)
    print(json.dumps({
        "tp": tp, "batch": b, "requests": n_req,
        "token_parity": g_on == g_off,
        "sampled_parity": outs_on == outs_off,  # informational: holds
        # unless device/host softmax rounding flips a draw
        "head_sample_ms_sharded": round(head_on, 3),
        "head_sample_ms_replicated": round(head_off, 3),
        "vocab_bytes_per_chip_sharded": led_on["vocab_bytes"],
        "vocab_bytes_per_chip_replicated": led_off["vocab_bytes"],
        "logits_ws_bytes_sharded": led_on["logits_workspace_bytes"],
        "logits_ws_bytes_replicated": led_off["logits_workspace_bytes"],
        "compiles_after_warmup_sharded": froz_on,
        "compiles_after_warmup_replicated": froz_off,
        "sampled_via_candidates": st_on.get("sharded", 0),
        "sampled_fallbacks": st_on.get("fallback", 0),
    }))


def _vocab_row(prefix: str) -> dict:
    """BENCH_VOCAB=1: the vocab-sharding A/B (ISSUE-15) — sharded vs
    replicated embedding+head on the same mixed greedy/sampled trace,
    greedy tokens asserted IDENTICAL, per-chip embedding+wcls bytes and
    the head+sample ms on the row, zero frozen-ledger compiles per
    variant. Runs in a child process: the tp mesh needs virtual CPU
    devices, and XLA parses that flag once per process."""
    env = dict(os.environ)
    env["BENCH_VOCAB_CHILD"] = "1"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                       capture_output=True, text=True, timeout=900,
                       env=env, cwd=os.path.dirname(os.path.abspath(__file__)))
    if r.returncode != 0:
        return {"metric": f"{prefix}_vocab_shard_head_sample_ms",
                "value": None, "unit": "ms",
                "error": (r.stderr or r.stdout)[-400:]}
    child = json.loads(r.stdout.strip().splitlines()[-1])
    assert child["token_parity"], "vocab-sharded greedy tokens diverged"
    row = {
        "metric": f"{prefix}_vocab_shard_head_sample_ms",
        "value": child["head_sample_ms_sharded"], "unit": "ms",
        "vs_baseline": None,
        "vs_replicated": (round(child["head_sample_ms_sharded"]
                                / child["head_sample_ms_replicated"], 3)
                          if child["head_sample_ms_replicated"] else None),
    }
    row.update(child)
    return row


def main() -> None:
    if os.environ.get("BENCH_VOCAB_CHILD"):
        _vocab_child()
        return
    model = os.environ.get("BENCH_MODEL", "7b")
    # 512-token decode: the ~140 ms tunnel dispatch cost amortizes to
    # <0.3 ms/token and attention runs at realistic steady-state fill
    n_tokens = int(os.environ.get("BENCH_TOKENS", "512"))
    spec = {"7b": LLAMA2_7B, "8b": LLAMA3_8B, "13b": LLAMA2_13B,
            "moe": MIXTRAL_MOE, "grok": GROK1_TRUNC,
            "70bt": LLAMA2_70B_TRUNC}.get(model, TINY)
    # long-context variants: BENCH_SEQ widens the cache, BENCH_FILL starts
    # decode at a deep fill (the flash kernel reads ~fill bytes of cache)
    seq = int(os.environ.get("BENCH_SEQ", str(min(spec.seq_len, 2048))))
    fill = int(os.environ.get("BENCH_FILL", "0"))
    assert 0 <= fill < seq - 1, f"BENCH_FILL={fill} must be < BENCH_SEQ-1={seq - 1}"
    if seq != spec.seq_len:
        spec = dataclasses.replace(spec, seq_len=seq)
    cache_dtype = (jnp.float8_e4m3fn if os.environ.get("BENCH_CACHE") == "f8"
                   else jnp.bfloat16)
    # decode must fit the KV cache: decode_greedy_device has no per-step
    # overflow guard, so steps past seq_len would silently measure garbage
    n_tokens = min(n_tokens, seq - fill - 1)

    metric = {"7b": "llama2_7b_q40_decode_ms_per_token_1chip",
              "8b": "llama3_8b_q40_decode_ms_per_token_1chip",
              "13b": "llama2_13b_q40_decode_ms_per_token_1chip",
              "moe": "mixtral_moe_q40_decode_ms_per_token_1chip",
              "grok": "grok1_fullwidth_q40_decode_ms_per_token_1chip",
              "70bt": "llama2_70b_width_q40_decode_ms_per_token_1chip"}.get(
        model, "tiny_llama_q40_decode_ms_per_token")
    base = {"7b": BASELINE_MS_PER_TOKEN,
            "8b": BASELINE_8B_MS_PER_TOKEN,
            "13b": BASELINE_13B_MS_PER_TOKEN,
            "tiny": BASELINE_MS_PER_TOKEN}.get(model)  # no published MoE row

    # the JSON line exists (value: null) before any jax work: every failure
    # past this point still prints it, annotated, instead of a traceback
    out: dict = {"metric": metric, "value": None, "unit": "ms/token",
                 "vs_baseline": None}
    def emit(row: dict) -> None:
        out.setdefault("variants", []).append(row)
        print(json.dumps(row), file=sys.stderr, flush=True)

    # a driver-side `timeout` delivers SIGTERM: flush whatever was measured
    # as the one stdout JSON line instead of dying silently — the full
    # variant ladder runs ~25 min on the tunneled chip, and losing the
    # already-measured main row to a deadline would waste the whole run
    import signal

    def _flush_and_exit(signum, frame):
        out.setdefault("error", "terminated (driver timeout?) — "
                                "partial rows kept")
        print(json.dumps(out), flush=True)
        sys.exit(0)

    try:
        signal.signal(signal.SIGTERM, _flush_and_exit)
    except (ValueError, OSError):  # non-main thread / exotic platform
        pass

    try:
        if os.environ.get("BENCH_PLATFORM"):
            jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
        probe_err = _probe_backend()
        if probe_err is not None:
            out["error"] = f"tpu backend unavailable: {probe_err}"
            print(json.dumps(out))
            return

        params = synth_q40_params(spec)
        engine = Engine(
            spec, params,
            compute_dtype=jnp.bfloat16, cache_dtype=cache_dtype,
            max_seq_len=seq)

        repeats = int(os.environ.get("BENCH_REPEATS", "2"))

        def _main():
            row = _decode_row(
                metric, spec, _measure_decode(engine, n_tokens, fill,
                                              repeats),
                fill=fill, n_tokens=n_tokens,
                cache_itemsize=jnp.dtype(cache_dtype).itemsize, base=base)
            _note_hbm(engine)
            return row

        main_row = _with_step_timeline(_main)
        ms_per_token = main_row["value"]
        out.update(main_row)
        if model in ("moe", "grok", "70bt"):
            # truncated-depth configs: the per-layer cost is the number
            # that extrapolates to full depth (includes the shared
            # wcls/embedding read spread over the resident layers — the
            # true per-layer weight read is slightly lower; full-depth
            # runs amortize the head further)
            out["ms_per_token_per_layer"] = round(
                ms_per_token / spec.n_layers, 4)
        print(json.dumps(out), file=sys.stderr, flush=True)
        if os.environ.get("BENCH_SIMULATE_OUTAGE"):  # test hook
            raise RuntimeError("simulated mid-run outage")

        if os.environ.get("BENCH_SERVE", "0") != "0":
            # continuous-batching serving row (runtime/scheduler.py) —
            # behind a flag so the default bench ladder stays fast; the
            # driver opts in with BENCH_SERVE=1 for the serving A/B
            emit(_with_step_timeline(_serve_row, params, spec,
                                     prefix=metric.split("_decode")[0]))

        if os.environ.get("BENCH_AUTOTUNE", "0") != "0":
            # the closed batch-knee loop (tools/autotune.py +
            # runtime/profiler.resolve_auto_shape + the SLO-aware
            # adaptive scheduler): calibrate, auto-size, then A/B the
            # self-tuned policy against every swept static setting on
            # goodput-at-SLO with greedy token parity and zero
            # post-warmup compiles asserted on the row
            emit(_with_step_timeline(_autotune_row, params, spec,
                                     prefix=metric.split("_decode")[0]))

        if os.environ.get("BENCH_PREFIX", "0") != "0":
            # radix prefix-cache row (runtime/prefix_cache.py): the
            # shared-system-prompt trace served cache OFF vs ON —
            # prefill tokens saved %, TTFT delta, greedy token parity
            emit(_with_step_timeline(_prefix_row, params, spec,
                                     prefix=metric.split("_decode")[0]))

        if os.environ.get("BENCH_ROUTER", "0") != "0":
            # multi-replica router row (runtime/router.py): the shared-
            # prefix trace at 2 replicas, cache-aware vs round-robin
            # placement, with one replica killed mid-trace — hit-rate
            # gain, availability %, zero-unstreamed-failure count
            # BENCH_ROUTER_PROCS selects the tier(s): "1" (default) =
            # thread row + process row, "0" = thread row only, "only" =
            # process row only (the smoke tests pick one each)
            procs_knob = os.environ.get("BENCH_ROUTER_PROCS", "1")
            if procs_knob != "only":
                emit(_with_step_timeline(
                    _router_row, params, spec,
                    prefix=metric.split("_decode")[0]))
            if procs_knob != "0":
                # process-mode row (runtime/replica_worker.py): two real
                # worker OS processes, one SIGKILLed mid-trace —
                # respawn-to-routable latency, availability %, zero
                # unstreamed failures, token parity
                emit(_router_procs_row(prefix=metric.split("_decode")[0]))

        if os.environ.get("BENCH_FLEET", "0") != "0":
            # fleet-brain chaos row (runtime/fleet.py, ISSUE-18): two
            # tenants through a 10x Poisson spike + one SIGKILL under
            # the autoscaling controller — victim p99 TTFT at SLO,
            # replicas visibly scaling, zero unstreamed failures
            emit(_fleet_row(prefix=metric.split("_decode")[0]))

        if os.environ.get("BENCH_KVX", "0") != "0":
            # cross-replica KV block transfer row (runtime/
            # kv_transfer.py): the shared-prefix trace with cold-replica
            # fills OFF vs ON (TTFT p50, fill hit rate, measured block
            # frames reconciled against the frame arithmetic, greedy
            # parity, zero frozen-ledger compiles) plus the
            # disaggregated prefill/decode A/B against a unified tier
            emit(_with_step_timeline(_kvx_row, params, spec,
                                     prefix=metric.split("_decode")[0]))

        if os.environ.get("BENCH_VOCAB", "0") != "0":
            # vocab-sharding A/B row (ops/sharded_vocab.py, ISSUE-15):
            # sharded vs replicated embedding+head on the same trace,
            # greedy parity asserted, per-chip vocab bytes + head ms
            # (child process: the tp mesh needs virtual devices)
            emit(_vocab_row(prefix=metric.split("_decode")[0]))

        if os.environ.get("BENCH_SPEC", "0") != "0":
            # real-draft speculative decoding row (runtime/draft.py):
            # self-draft vs prompt-lookup vs plain greedy on a
            # fixed-seed NON-repetitive eval (measured accept rate +
            # repetitiveness label on the row — the VERDICT #6
            # reporting debt), plus the per-slot Poisson serving A/B
            # with the compile ledger frozen
            emit(_with_step_timeline(_spec_row,
                                     prefix=metric.split("_decode")[0]))

        if os.environ.get("BENCH_CHAOS", "0") != "0":
            # resilience row (runtime/resilience.py): the Poisson trace
            # replayed with injected mid-trace crashes — availability %,
            # recovered-request counts, recovery p50
            emit(_with_step_timeline(_chaos_row, params, spec,
                                     prefix=metric.split("_decode")[0]))
            # cluster row (parallel/multihost.py): two-process control-
            # plane chaos — worker death/stall -> structured detection
            # latency, bounded by --worker-timeout — plus the measured
            # wire plane (dlwire): a clean run's per-peer byte/RTT
            # ledger as the row's `wire` block, heartbeat round trips
            # as its step_timeline, and the exact frame-arithmetic
            # reconciliation
            emit(_with_step_timeline(
                _cluster_chaos_row, prefix=metric.split("_decode")[0]))

        # extra capability rows, measured in the same run (driver default
        # config only — explicit BENCH_* overrides mean a targeted A/B)
        defaults = (model == "7b" and fill == 0 and seq == 2048
                    and cache_dtype == jnp.bfloat16)
        if defaults and os.environ.get("BENCH_VARIANTS", "1") != "0":
            import gc

            _variant_rows(engine, params, spec, repeats, emit)
            del engine, params  # free the 7b weights before the MoE rows
            gc.collect()
            emit(_with_step_timeline(_moe_row, repeats))
            emit(_with_step_timeline(_grok_row, repeats))
    except Exception as e:  # partial rows survive outages and Ctrl-C;
        # SIGTERM (a driver `timeout`) exits 0 via _flush_and_exit with an
        # "error" annotation — consumers must check the error FIELD, not
        # the exit code, to distinguish partial from complete runs
        out["error"] = f"{type(e).__name__}: {e}"[:400]
        print(json.dumps(out), flush=True)
        return

    print(json.dumps(out))


if __name__ == "__main__":
    main()
